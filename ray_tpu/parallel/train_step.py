"""Sharded train-state construction and jitted train steps.

This is the compute core the JaxTrainer drives. Where the reference's
DataParallelTrainer relies on torch DDP doing gradient allreduce inside
torch (reference: python/ray/train/torch/config.py:66,153 +
rllib/core/learner/torch/torch_learner.py:533), here the whole training
step — forward, backward, gradient reduction, optimizer update — is ONE
jitted XLA program over the mesh: param shardings (fsdp/model axes) make
GSPMD emit all-gather/reduce-scatter/psum over ICI automatically.

Donation: params and opt_state are donated so the update is in-place in
HBM (no double-buffering of the model).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import batch_spec


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def default_optimizer(
    learning_rate: float = 3e-4,
    *,
    warmup_steps: int = 100,
    total_steps: int = 10000,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
    b1: float = 0.9,
    b2: float = 0.95,
) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def state_shardings(
    mesh: Mesh,
    param_specs: Any,
    init_fn: Callable[[], TrainState],
) -> Tuple[TrainState, Any]:
    """Compute NamedShardings for a TrainState produced by init_fn.

    Optimizer-state subtrees that are param-shaped pytrees (adam
    moments, ema copies) get the parameter shardings, matched
    STRUCTURALLY — any subtree whose treedef equals the params' treedef
    takes param_specs wholesale. Everything else (counts, schedule
    scalars) replicates.
    """
    shape_tree = jax.eval_shape(init_fn)
    params_treedef = jax.tree_util.tree_structure(shape_tree.params)

    def to_sharding(spec_tree):
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec),
            spec_tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    def map_opt(node):
        if jax.tree_util.tree_structure(node) == params_treedef:
            return to_sharding(param_specs)
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # namedtuple
            return type(node)(*[map_opt(x) for x in node])
        if isinstance(node, (tuple, list)):
            return type(node)(map_opt(x) for x in node)
        if isinstance(node, dict):
            return {k: map_opt(v) for k, v in node.items()}
        return jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P()), node
        )

    param_sh = to_sharding(param_specs)
    opt_sh = map_opt(shape_tree.opt_state)
    step_sh = NamedSharding(mesh, P())
    return TrainState(step_sh, param_sh, opt_sh), shape_tree


def create_train_state(
    mesh: Mesh,
    rng: jax.Array,
    init_params_fn: Callable[[jax.Array], Any],
    optimizer: optax.GradientTransformation,
    param_specs: Any,
) -> Tuple[TrainState, TrainState]:
    """Initialize a sharded TrainState directly on the mesh.

    Init runs under jit with out_shardings, so every parameter is
    created already-sharded (no host-memory staging of an 8B model).
    Returns (state, state_shardings).
    """

    def init_fn():
        params = init_params_fn(rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
        )

    shardings, _ = state_shardings(mesh, param_specs, init_fn)
    state = jax.jit(init_fn, out_shardings=shardings)()
    return state, shardings


def make_train_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    state_sh: TrainState,
    *,
    batch_ndim_extra: int = 1,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the donated, sharded train step.

    loss_fn(params, batch) -> scalar. Batch arrays are sharded on dim0
    over the (data, fsdp) axes.
    """
    bspec = NamedSharding(mesh, batch_spec(batch_ndim_extra))

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm, "step": state.step + 1}
        return TrainState(state.step + 1, new_params, new_opt), metrics

    return jax.jit(
        step,
        in_shardings=(state_sh, bspec),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def make_eval_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    mesh: Mesh,
    state_sh: TrainState,
    *,
    batch_ndim_extra: int = 1,
) -> Callable:
    bspec = NamedSharding(mesh, batch_spec(batch_ndim_extra))

    def step(state: TrainState, batch):
        return {"loss": loss_fn(state.params, batch)}

    return jax.jit(step, in_shardings=(state_sh, bspec),
                   out_shardings=NamedSharding(mesh, P()))
