"""Parallelism layer: meshes, shardings, train steps, pipeline/sequence
parallel schedules. See ray_tpu.parallel.mesh for the axis conventions."""

from .mesh import (
    AXIS_ORDER,
    BATCH_AXES,
    MeshConfig,
    batch_sharding,
    batch_spec,
    dp_degree,
    make_mesh,
    mesh_axis_size,
    single_device_mesh,
)
from .pipeline import pipeline_apply, pipeline_sharded
from .train_step import (
    TrainState,
    create_train_state,
    default_optimizer,
    make_eval_step,
    make_train_step,
    state_shardings,
)

__all__ = [
    "AXIS_ORDER",
    "BATCH_AXES",
    "MeshConfig",
    "batch_sharding",
    "batch_spec",
    "dp_degree",
    "make_mesh",
    "mesh_axis_size",
    "single_device_mesh",
    "TrainState",
    "pipeline_apply",
    "pipeline_sharded",
    "create_train_state",
    "default_optimizer",
    "make_eval_step",
    "make_train_step",
    "state_shardings",
]
