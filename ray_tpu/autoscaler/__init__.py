"""Autoscaler: declarative node-count reconciliation from demand.

Parity: python/ray/autoscaler/v2/ (autoscaler.py:42 + scheduler.py
bin-packing over ClusterStatus, instance_manager reconciler) — the
TPU-native reduction: the hub already aggregates pending demand
(list_state("demand")); the autoscaler bin-packs unmet shapes against
configured node types, asks a NodeProvider for instances, and retires
nodes idle past the timeout. Providers plug in like the reference's
NodeProvider ABC (aws/gcp/kuberay/fake_multinode); LocalNodeProvider is
the fake_multinode equivalent — real node-agent processes on this host
— and the shape a GKE/TPU-pod provider implements for production.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeTypeConfig:
    """One launchable node shape (reference: available_node_types)."""

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 4


class NodeProvider:
    """Reference: autoscaler/node_provider.py ABC."""

    def create_node(self, node_type: NodeTypeConfig) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Simulated instances: node-agent processes on this host (the
    reference's fake_multinode provider)."""

    def __init__(self, cluster):
        self._cluster = cluster  # ray_tpu.cluster_utils.Cluster
        self._nodes: Dict[str, Any] = {}

    def create_node(self, node_type: NodeTypeConfig) -> str:
        res = dict(node_type.resources)
        cpus = int(res.pop("CPU", 1))
        tpus = int(res.pop("TPU", 0))
        res.pop("memory", None)
        node = self._cluster.add_node(
            num_cpus=cpus, num_tpus=tpus, resources=res or None
        )
        self._nodes[node.node_id] = node
        return node.node_id

    def terminate_node(self, node_id: str) -> None:
        node = self._nodes.pop(node_id, None)
        if node is not None:
            self._cluster.remove_node(node)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)


def _fits(shape: Dict[str, float], resources: Dict[str, float]) -> bool:
    return all(resources.get(k, 0.0) >= v for k, v in shape.items())


class Autoscaler:
    def __init__(
        self,
        provider: NodeProvider,
        node_types: List[NodeTypeConfig],
        *,
        poll_interval_s: float = 0.5,
        upscale_delay_s: float = 0.5,
        idle_timeout_s: float = 30.0,
    ):
        self.provider = provider
        self.node_types = node_types
        self.poll_interval_s = poll_interval_s
        self.upscale_delay_s = upscale_delay_s
        self.idle_timeout_s = idle_timeout_s
        self._demand_since: Optional[float] = None
        self._idle_since: Dict[str, float] = {}
        self._owned_type: Dict[str, NodeTypeConfig] = {}
        self._launched_at: Dict[str, float] = {}
        self.launch_grace_s = 30.0  # registration time before a missing
        # node counts as dead (out-of-band failure)
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def _client(self):
        from ray_tpu._private import worker

        return worker.get_client()

    def step(self) -> None:
        """One reconcile pass (the reference's Autoscaler.update)."""
        client = self._client()
        # post-quota demand only: work parked by a tenant's admission
        # quota (fairsched pending_quota) is reported flagged and MUST
        # NOT drive scale-up — no amount of new nodes can dispatch it,
        # and buying hardware a quota forbids using defeats the quota
        demand = [
            d for d in client.list_state("demand")
            if not d.get("pending_quota")
        ]
        avail_nodes = {
            n["node_id"]: n for n in client.list_state("nodes") if n["alive"]
        }
        # unmet demand: shapes no live node could EVER satisfy right now
        unmet = [
            d for d in demand
            if not any(
                _fits(d["shape"], n["available"]) for n in avail_nodes.values()
            )
        ]
        now = time.monotonic()
        if unmet:
            if self._demand_since is None:
                self._demand_since = now
            if now - self._demand_since >= self.upscale_delay_s:
                self._scale_up(unmet)
                self._demand_since = None
        else:
            self._demand_since = None
        self._maybe_scale_down(avail_nodes, client)

    def _scale_up(self, unmet: List[dict]) -> None:
        counts: Dict[str, int] = {}
        for nid, nt in self._owned_type.items():
            counts[nt.name] = counts.get(nt.name, 0) + 1
        for d in unmet:
            for nt in self.node_types:
                if not _fits(d["shape"], nt.resources):
                    continue
                if counts.get(nt.name, 0) >= nt.max_workers:
                    continue
                # one node per unmet shape per pass (launch pacing)
                node_id = self.provider.create_node(nt)
                self._owned_type[node_id] = nt
                self._launched_at[node_id] = time.monotonic()
                counts[nt.name] = counts.get(nt.name, 0) + 1
                break

    def _maybe_scale_down(self, avail_nodes, client) -> None:
        now = time.monotonic()
        # nodes that died out-of-band must release their max_workers
        # budget (and provider bookkeeping) or that type can never scale;
        # a launch grace period keeps this from racing registration
        for node_id in list(self._owned_type):
            if node_id not in avail_nodes and (
                now - self._launched_at.get(node_id, now) > self.launch_grace_s
            ):
                try:
                    self.provider.terminate_node(node_id)
                except Exception:
                    pass
                self._owned_type.pop(node_id, None)
                self._idle_since.pop(node_id, None)
                self._launched_at.pop(node_id, None)
        busy_nodes = {
            w["node_id"]
            for w in client.list_state("workers")
            if w["state"] in ("busy", "actor")
        }
        # quota-parked demand must not hold idle nodes alive either
        demand = [
            d for d in client.list_state("demand")
            if not d.get("pending_quota")
        ]
        for node_id in list(self._owned_type):
            node = avail_nodes.get(node_id)
            nt = self._owned_type[node_id]
            idle = (
                node is not None
                and node_id not in busy_nodes
                and node["available"] == node["resources"]
                and not demand
            )
            if not idle:
                self._idle_since.pop(node_id, None)
                continue
            first = self._idle_since.setdefault(node_id, now)
            owned_of_type = sum(
                1 for t in self._owned_type.values() if t.name == nt.name
            )
            if (
                now - first >= self.idle_timeout_s
                and owned_of_type > nt.min_workers
            ):
                self.provider.terminate_node(node_id)
                self._owned_type.pop(node_id, None)
                self._idle_since.pop(node_id, None)

    # ------------------------------------------------------------------
    def start(self) -> "Autoscaler":
        self._running = True

        def loop():
            import sys
            import traceback

            while self._running:
                try:
                    self.step()
                except Exception:
                    # transient control-plane hiccups must not kill the
                    # loop, but they must be visible
                    sys.stderr.write(
                        f"[ray_tpu] autoscaler step failed:\n"
                        f"{traceback.format_exc()}\n"
                    )
                time.sleep(self.poll_interval_s)

        self._thread = threading.Thread(
            target=loop, daemon=True, name="ray-tpu-autoscaler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
