"""Native (C++) runtime components, built on demand with g++.

The reference implements its channel/object plane in C++
(src/ray/core_worker/experimental_mutable_object_manager.h, plasma in
src/ray/object_manager/plasma/); this package holds the TPU-native
equivalents. Modules are compiled once per host into a cache dir keyed
by source hash, so a fresh checkout pays one ~2s g++ run and every
process after that dlopens the cached .so. Falls back cleanly (callers
check ``ring_native() is None``) when no toolchain is available.
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading
from typing import Optional

_SRC_DIR = os.path.dirname(__file__)
_lock = threading.Lock()
_ring_mod = None
_ring_tried = False


def _cache_dir() -> str:
    base = os.environ.get("RAY_TPU_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "ray_tpu_native"
    )
    os.makedirs(base, exist_ok=True)
    return base


def _build(mod_name: str, src_name: str) -> Optional[str]:
    """Compile src under _native/ into the cache; return the .so path."""
    src = os.path.join(_SRC_DIR, src_name)
    with open(src, "rb") as f:
        tag = hashlib.sha256(
            f.read() + sys.version.encode()
        ).hexdigest()[:16]
    so_path = os.path.join(_cache_dir(), f"{mod_name}_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    include = sysconfig.get_paths()["include"]
    tmp = so_path + f".tmp.{os.getpid()}"
    cmd = [
        "g++",
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        f"-I{include}",
        src,
        "-o",
        tmp,
        "-lrt",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)  # atomic: concurrent builders race safely
        return so_path
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load(mod_name: str, so_path: str):
    spec = importlib.util.spec_from_file_location(mod_name, so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def ring_native():
    """The _ring_native extension module, or None when unavailable."""
    global _ring_mod, _ring_tried
    if _ring_tried:
        return _ring_mod
    with _lock:
        if _ring_tried:
            return _ring_mod
        if os.environ.get("RAY_TPU_DISABLE_NATIVE"):
            _ring_tried = True
            return None
        so_path = _build("_ring_native", "ring_channel.cpp")
        if so_path is not None:
            try:
                _ring_mod = _load("_ring_native", so_path)
            except Exception:
                _ring_mod = None
        _ring_tried = True
        return _ring_mod
