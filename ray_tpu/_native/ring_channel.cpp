// Native SPSC shared-memory ring channel.
//
// TPU-native equivalent of the reference's C++ mutable-object channel
// (src/ray/core_worker/experimental_mutable_object_manager.h,
// backing python/ray/experimental/channel/shared_memory_channel.py):
// a pre-allocated ring written in place per DAG execution, no
// allocation or serialization in the hot path. Compared to the Python
// ShmChannel ring (experimental/channel/shm_channel.py) this adds real
// acquire/release atomics (the Python path leans on the GIL + x86 TSO)
// and GIL-released adaptive spin waits: the Python poller's latency
// floor is its 500us sleep; this wakes in microseconds.
//
// Built by ray_tpu/_native/__init__.py with g++ via the CPython C API —
// no pybind11 (not in the image).
//
// Wire/layout compatibility: the Python and native rings use different
// segment layouts, so the backend choice is pinned in every pickled
// channel descriptor (ShmChannel.__reduce__, CompiledDAG desc()).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52547052494e4721ull;  // "RTpRING!"

struct RingHeader {
  uint64_t magic;
  uint64_t item_bytes;
  uint64_t capacity;
  uint64_t _pad;
  alignas(64) std::atomic<uint64_t> write_seq;
  alignas(64) std::atomic<uint64_t> read_seq;
};

struct Ring {
  RingHeader* hdr;
  std::atomic<uint64_t>* slot_seq;
  uint8_t* data;
  size_t total;
};

inline size_t ring_bytes(uint64_t item_bytes, uint64_t capacity) {
  return sizeof(RingHeader) + capacity * sizeof(std::atomic<uint64_t>) +
         capacity * item_bytes;
}

inline void map_views(Ring* r, void* base) {
  r->hdr = static_cast<RingHeader*>(base);
  r->slot_seq = reinterpret_cast<std::atomic<uint64_t>*>(
      static_cast<uint8_t*>(base) + sizeof(RingHeader));
  r->data = reinterpret_cast<uint8_t*>(r->slot_seq + r->hdr->capacity);
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

// Adaptive wait: spin with pause, then escalate to short nanosleeps.
// Returns false on deadline expiry.
template <typename Pred>
bool wait_until(Pred pred, double deadline) {
  for (int i = 0; i < 4096; ++i) {
    if (pred()) return true;
#if defined(__x86_64__)
    __builtin_ia32_pause();
#endif
  }
  struct timespec ts = {0, 1000};  // 1us, escalating to 100us
  while (!pred()) {
    if (now_s() > deadline) return false;
    nanosleep(&ts, nullptr);
    if (ts.tv_nsec < 100000) ts.tv_nsec *= 2;
  }
  return true;
}

void capsule_destructor(PyObject* cap) {
  Ring* r = static_cast<Ring*>(PyCapsule_GetPointer(cap, "ray_tpu.Ring"));
  if (r != nullptr) {
    munmap(r->hdr, r->total);
    delete r;
  }
}

Ring* get_ring(PyObject* cap) {
  return static_cast<Ring*>(PyCapsule_GetPointer(cap, "ray_tpu.Ring"));
}

PyObject* ring_create(PyObject*, PyObject* args) {
  const char* name;
  unsigned long long item_bytes, capacity;
  if (!PyArg_ParseTuple(args, "sKK", &name, &item_bytes, &capacity))
    return nullptr;
  size_t total = ring_bytes(item_bytes, capacity);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return PyErr_SetFromErrno(PyExc_OSError);
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return PyErr_SetFromErrno(PyExc_OSError);
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return PyErr_SetFromErrno(PyExc_OSError);
  }
  std::memset(base, 0, sizeof(RingHeader));
  auto* hdr = static_cast<RingHeader*>(base);
  hdr->item_bytes = item_bytes;
  hdr->capacity = capacity;
  hdr->write_seq.store(0, std::memory_order_relaxed);
  hdr->read_seq.store(0, std::memory_order_relaxed);
  auto* seq = reinterpret_cast<std::atomic<uint64_t>*>(
      static_cast<uint8_t*>(base) + sizeof(RingHeader));
  for (uint64_t i = 0; i < capacity; ++i)
    seq[i].store(0, std::memory_order_relaxed);
  hdr->magic = kMagic;  // publish last
  Ring* r = new Ring();
  r->total = total;
  map_views(r, base);
  return PyCapsule_New(r, "ray_tpu.Ring", capsule_destructor);
}

PyObject* ring_attach(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return PyErr_SetFromErrno(PyExc_OSError);
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return PyErr_SetFromErrno(PyExc_OSError);
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return PyErr_SetFromErrno(PyExc_OSError);
  auto* hdr = static_cast<RingHeader*>(base);
  if ((size_t)st.st_size < sizeof(RingHeader) || hdr->magic != kMagic ||
      ring_bytes(hdr->item_bytes, hdr->capacity) > (size_t)st.st_size) {
    munmap(base, st.st_size);
    PyErr_SetString(PyExc_ValueError, "not a ray_tpu ring segment");
    return nullptr;
  }
  Ring* r = new Ring();
  r->total = st.st_size;
  map_views(r, base);
  return PyCapsule_New(r, "ray_tpu.Ring", capsule_destructor);
}

PyObject* ring_unlink(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  shm_unlink(name);  // best-effort
  Py_RETURN_NONE;
}

PyObject* ring_write(PyObject*, PyObject* args) {
  PyObject* cap;
  Py_buffer buf;
  double timeout_s;
  if (!PyArg_ParseTuple(args, "Oy*d", &cap, &buf, &timeout_s)) return nullptr;
  Ring* r = get_ring(cap);
  if (r == nullptr) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  RingHeader* h = r->hdr;
  if ((uint64_t)buf.len != h->item_bytes) {
    PyBuffer_Release(&buf);
    PyErr_Format(PyExc_ValueError, "item is %zd bytes, ring expects %llu",
                 buf.len, (unsigned long long)h->item_bytes);
    return nullptr;
  }
  bool ok;
  uint64_t w;
  Py_BEGIN_ALLOW_THREADS;
  double deadline = now_s() + timeout_s;
  w = h->write_seq.load(std::memory_order_relaxed);
  uint64_t cap_n = h->capacity;
  ok = wait_until(
      [&] { return w - h->read_seq.load(std::memory_order_acquire) < cap_n; },
      deadline);
  if (ok) {
    uint64_t slot = w % cap_n;
    std::memcpy(r->data + slot * h->item_bytes, buf.buf, h->item_bytes);
    r->slot_seq[slot].store(w + 1, std::memory_order_release);
    h->write_seq.store(w + 1, std::memory_order_release);
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&buf);
  if (!ok) {
    PyErr_SetString(PyExc_TimeoutError, "ring full: reader not draining");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* ring_read_into(PyObject*, PyObject* args) {
  PyObject* cap;
  Py_buffer buf;
  double timeout_s;
  if (!PyArg_ParseTuple(args, "Ow*d", &cap, &buf, &timeout_s)) return nullptr;
  Ring* r = get_ring(cap);
  if (r == nullptr) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  RingHeader* h = r->hdr;
  if ((uint64_t)buf.len != h->item_bytes) {
    PyBuffer_Release(&buf);
    PyErr_Format(PyExc_ValueError, "out buffer is %zd bytes, ring item is %llu",
                 buf.len, (unsigned long long)h->item_bytes);
    return nullptr;
  }
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  double deadline = now_s() + timeout_s;
  uint64_t rd = h->read_seq.load(std::memory_order_relaxed);
  uint64_t slot = rd % h->capacity;
  ok = wait_until(
      [&] {
        return r->slot_seq[slot].load(std::memory_order_acquire) == rd + 1;
      },
      deadline);
  if (ok) {
    std::memcpy(buf.buf, r->data + slot * h->item_bytes, h->item_bytes);
    h->read_seq.store(rd + 1, std::memory_order_release);
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&buf);
  if (!ok) {
    PyErr_SetString(PyExc_TimeoutError, "ring empty: writer not producing");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* ring_try_read_into(PyObject*, PyObject* args) {
  PyObject* cap;
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "Ow*", &cap, &buf)) return nullptr;
  Ring* r = get_ring(cap);
  if (r == nullptr) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  RingHeader* h = r->hdr;
  uint64_t rd = h->read_seq.load(std::memory_order_relaxed);
  uint64_t slot = rd % h->capacity;
  bool ready =
      r->slot_seq[slot].load(std::memory_order_acquire) == rd + 1 &&
      (uint64_t)buf.len == h->item_bytes;
  if (ready) {
    std::memcpy(buf.buf, r->data + slot * h->item_bytes, h->item_bytes);
    h->read_seq.store(rd + 1, std::memory_order_release);
  }
  PyBuffer_Release(&buf);
  return PyBool_FromLong(ready ? 1 : 0);
}

PyObject* ring_info(PyObject*, PyObject* args) {
  PyObject* cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  Ring* r = get_ring(cap);
  if (r == nullptr) return nullptr;
  return Py_BuildValue(
      "{s:K,s:K,s:K,s:K}", "item_bytes",
      (unsigned long long)r->hdr->item_bytes, "capacity",
      (unsigned long long)r->hdr->capacity, "write_seq",
      (unsigned long long)r->hdr->write_seq.load(std::memory_order_acquire),
      "read_seq",
      (unsigned long long)r->hdr->read_seq.load(std::memory_order_acquire));
}

PyMethodDef methods[] = {
    {"create", ring_create, METH_VARARGS,
     "create(name, item_bytes, capacity) -> ring handle"},
    {"attach", ring_attach, METH_VARARGS, "attach(name) -> ring handle"},
    {"unlink", ring_unlink, METH_VARARGS, "unlink(name)"},
    {"write", ring_write, METH_VARARGS,
     "write(ring, buffer, timeout_s); blocks while full"},
    {"read_into", ring_read_into, METH_VARARGS,
     "read_into(ring, out_buffer, timeout_s); blocks until published"},
    {"try_read_into", ring_try_read_into, METH_VARARGS,
     "try_read_into(ring, out_buffer) -> bool"},
    {"info", ring_info, METH_VARARGS, "info(ring) -> dict"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_ring_native",
                         "native SPSC shm ring channel", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__ring_native(void) { return PyModule_Create(&moduledef); }
