"""`python -m ray_tpu` — the cluster CLI.

Parity: the reference's `ray` CLI (python/ray/scripts/scripts.py:
start/stop/status/timeline/memory/debug), the state CLI
(`ray list ...`, python/ray/util/state/state_cli.py) and the job CLI
(`ray job submit/status/logs/stop/list`,
python/ray/dashboard/modules/job/cli.py). One argparse tree, no
external CLI framework.

    python -m ray_tpu start --head --port 7777        # head (blocks)
    python -m ray_tpu start --address tcp://ip:7777   # join as a node
    python -m ray_tpu status
    python -m ray_tpu list actors
    python -m ray_tpu jobs                            # tenants vs quota
    python -m ray_tpu summary tasks
    python -m ray_tpu trace                           # sampled traces
    python -m ray_tpu trace <trace_id>                # critical path
    python -m ray_tpu chaos                           # fault injection
    python -m ray_tpu timeline --output /tmp/tl.json
    python -m ray_tpu memory --leak-suspects
    python -m ray_tpu stack <worker-id|hub|pid>       # remote stacks
    python -m ray_tpu profile --duration 5 --fold out.txt
    python -m ray_tpu job submit -- python train.py
    python -m ray_tpu job logs <id>
    python -m ray_tpu debug
    python -m ray_tpu stop
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import List, Optional

_STATE_DIR = os.path.join(os.path.expanduser("~"), ".ray_tpu")
_ADDR_FILE = os.path.join(_STATE_DIR, "head_address")
_PID_FILE = os.path.join(_STATE_DIR, "head_pid")


# ------------------------------------------------------------------ helpers
def _resolve_address(explicit: Optional[str]) -> Optional[str]:
    if explicit:
        return explicit
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    try:
        with open(_ADDR_FILE) as f:
            return f.read().strip() or None
    except OSError:
        return None


def _connect(args) -> None:
    import ray_tpu

    addr = _resolve_address(getattr(args, "address", None))
    if addr is None:
        raise SystemExit(
            "no cluster address: pass --address, set RAY_TPU_ADDRESS, or "
            "run `python -m ray_tpu start --head` first"
        )
    ray_tpu.init(address=addr, ignore_reinit_error=True)


def _print_table(rows: List[dict], columns: List[str]) -> None:
    if not rows:
        print("(none)")
        return
    widths = {
        c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in columns
    }
    header = "  ".join(c.upper().ljust(widths[c]) for c in columns)
    print(header)
    print("-" * len(header))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns))


# ------------------------------------------------------------------ commands
def cmd_start(args) -> None:
    os.makedirs(_STATE_DIR, exist_ok=True)
    if args.head:
        import ray_tpu

        if args.hub_shards is not None:
            # the hub reads config at construction; env is the handoff
            os.environ["RAY_TPU_HUB_SHARDS"] = str(args.hub_shards)
        ctx = ray_tpu.init(
            num_cpus=args.num_cpus,
            num_tpus=args.num_tpus,
            max_workers=args.max_workers,
            _tcp_hub=True,
            _hub_host=args.host,
            _hub_port=args.port,
        )
        addr = ctx.address_info["address"]
        with open(_ADDR_FILE, "w") as f:
            f.write(addr)
        with open(_PID_FILE, "w") as f:
            f.write(str(os.getpid()))
        print(f"ray_tpu head started at {addr}")
        print("connect with: ray_tpu.init(address=" + repr(addr) + ")")
        print("stop with: python -m ray_tpu stop")

        def _on_sigterm(signum, frame):
            # post-mortem before dying: dump the flight recorder (the
            # hub thread is still alive here), then reuse the Ctrl-C
            # teardown path below
            from ray_tpu._private import worker as _worker

            if _worker._hub is not None:
                try:
                    path = _worker._hub.dump_flight_recorder("sigterm")
                    print(f"flight recorder dumped to {path}", flush=True)
                except Exception:
                    pass
            raise KeyboardInterrupt

        signal.signal(signal.SIGTERM, _on_sigterm)
        # Head blocks for its lifetime (reference: ray start --block; a
        # non-blocking daemonizing head adds nothing on one host where
        # drivers embed the hub in-process anyway). Exits on Ctrl-C /
        # SIGTERM, or when the hub reactor stops — a wire-level
        # SHUTDOWN (`ray_tpu stop` from a remote operator) must bring
        # the whole process down, not leave a zombie head with a dead
        # reactor.
        try:
            from ray_tpu._private import worker as _worker

            while _worker._hub is not None and _worker._hub.thread.is_alive():
                _worker._hub.thread.join(timeout=3.0)
        except KeyboardInterrupt:
            pass
        finally:
            # Ctrl-C is the normal way to stop a blocking head: leaving
            # the address/pid files behind would point later CLI calls
            # at a dead endpoint (or a recycled pid)
            for path in (_PID_FILE, _ADDR_FILE):
                try:
                    os.unlink(path)
                except OSError:
                    pass
        return
    # join an existing cluster as a node agent (reference: ray start
    # --address=...)
    addr = _resolve_address(args.address)
    if addr is None:
        raise SystemExit("start: need --head or --address tcp://host:port")
    from ray_tpu._private.session import new_session_dir

    node_id = args.node_id or f"cli-node-{os.getpid()}"
    # a pre-set RAY_TPU_SESSION_DIR is honored (deployments may point
    # cleanup/co-located tooling at a known path)
    session_dir = os.environ.get("RAY_TPU_SESSION_DIR") or new_session_dir(
        f"ray_tpu_{node_id}"
    )
    env = dict(os.environ)
    env.update(
        RAY_TPU_HUB_ADDR=addr,
        RAY_TPU_NODE_ID=node_id,
        RAY_TPU_SESSION_DIR=session_dir,
        RAY_TPU_NUM_CPUS=str(args.num_cpus or (os.cpu_count() or 1)),
    )
    if args.num_tpus is not None:
        env["RAY_TPU_NUM_TPUS"] = str(args.num_tpus)
    os.execve(
        sys.executable,
        [sys.executable, "-m", "ray_tpu._private.node_agent"],
        env,
    )


def cmd_stop(args) -> None:
    try:
        with open(_PID_FILE) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        # No local pid (the head runs remotely, or another user started
        # it): ask the hub itself over the wire. SHUTDOWN flips the
        # reactor's running flag; the hub tears the session down exactly
        # as it would on SIGINT.
        addr = _resolve_address(getattr(args, "address", None))
        if addr is None:
            raise SystemExit("no recorded head pid (was `start --head` used?)")
        from ._private import protocol as P
        from ._private.client import connect_hub
        from ._private.serialization import dumps_frame

        try:
            conn = connect_hub(addr)
            try:
                conn.send_bytes(dumps_frame((P.SHUTDOWN, {})))
            finally:
                conn.close()
        except OSError as err:
            # dead hub / stale address (e.g. RAY_TPU_ADDRESS left
            # exported after the head went down): report, don't
            # traceback — and still drop the stale state files below
            print(f"hub at {addr} unreachable ({err}); nothing to stop")
        else:
            print(f"sent shutdown to hub at {addr}")
        for path in (_PID_FILE, _ADDR_FILE):
            try:
                os.unlink(path)
            except OSError:
                pass
        return
    try:
        os.kill(pid, signal.SIGINT)
        print(f"sent SIGINT to head (pid {pid})")
    except ProcessLookupError:
        print("head already gone")
    for path in (_PID_FILE, _ADDR_FILE):
        try:
            os.unlink(path)
        except OSError:
            pass


def cmd_status(args) -> None:
    import ray_tpu

    _connect(args)
    nodes = ray_tpu.nodes()
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    print(f"nodes: {len(nodes)}")
    _print_table(
        [
            {
                "node_id": n["node_id"],
                "alive": n["alive"],
                "hostname": n.get("hostname", ""),
                "cpu": n.get("resources", {}).get("CPU", 0),
                "tpu": n.get("resources", {}).get("TPU", 0),
            }
            for n in nodes
        ],
        ["node_id", "alive", "hostname", "cpu", "tpu"],
    )
    print("\nresources (available / total):")
    for key in sorted(total):
        print(f"  {key}: {avail.get(key, 0):g} / {total[key]:g}")


_LIST_COLUMNS = {
    "actors": ["actor_id", "class_name", "state", "name", "pid"],
    "tasks": ["task_id", "name", "state", "worker_id"],
    "workers": ["worker_id", "node_id", "pid", "state"],
    "nodes": ["node_id", "alive", "hostname"],
    "objects": ["object_id", "kind", "size", "owner", "owner_alive",
                "age_s", "pins", "ready", "spilled"],
    "profile": ["pid", "kind", "thread", "stage", "task_name", "samples"],
    "placement_groups": ["pg_id", "state", "strategy"],
    "jobs": ["job_id", "tenant", "priority", "quota", "submitted",
             "dispatched", "preempted"],
    "tenants": ["tenant", "quota", "admitted", "share", "pending_quota"],
    "shards": ["shard", "service", "conns", "accepted", "wakeups",
               "frames_sent", "drain_saturated", "backpressure",
               "processed"],
    "traces": ["trace_id", "root", "n_spans", "duration_s", "processes"],
}


def cmd_list(args) -> None:
    from ray_tpu.util import state as state_api

    _connect(args)
    kind = {"pgs": "placement_groups"}.get(args.kind, args.kind)
    fn = getattr(state_api, f"list_{kind}")
    rows = fn()
    if args.format == "json":
        print(json.dumps(rows, indent=2, default=str))
        return
    cols = _LIST_COLUMNS.get(kind) or (list(rows[0].keys()) if rows else [])
    _print_table(rows, cols)


def cmd_summary(args) -> None:
    from ray_tpu.util import state as state_api

    _connect(args)
    fn = getattr(state_api, f"summarize_{args.kind}")
    print(json.dumps(fn(), indent=2, default=str))


def cmd_events(args) -> None:
    """Flight-recorder runtime events (node up/down, worker exits,
    retries, spills...; reference: `ray list cluster-events`)."""
    from ray_tpu.util import state as state_api

    _connect(args)
    events = state_api.list_events()
    if args.kind:
        events = [e for e in events if e.get("kind") == args.kind]
    if args.format == "json":
        print(json.dumps(events, indent=2, default=str))
        return
    rows = []
    for e in events:
        detail = " ".join(
            f"{k}={v}" for k, v in e.items()
            if k not in ("seq", "ts", "kind")
        )
        rows.append({
            "seq": e.get("seq", ""),
            "time": time.strftime(
                "%H:%M:%S", time.localtime(e.get("ts", 0))
            ),
            "kind": e.get("kind", ""),
            "detail": detail[:120],
        })
    _print_table(rows, ["seq", "time", "kind", "detail"])


def cmd_trace(args) -> None:
    """Distributed runtime traces (util/tracing.py). Without an id:
    list sampled traces. With one: the span table + the critical-path
    breakdown (which stage the time went to)."""
    from ray_tpu.util import state as state_api
    from ray_tpu.util.tracing import analyze_trace

    _connect(args)
    if not args.trace_id:
        rows = state_api.list_traces()
        if args.format == "json":
            print(json.dumps(rows, indent=2, default=str))
            return
        _print_table(
            [
                {
                    "trace_id": r["trace_id"],
                    "root": r.get("root", ""),
                    "spans": r.get("n_spans", 0),
                    "duration_ms": f"{1000 * r.get('duration_s', 0):.1f}",
                    "processes": r.get("processes", 0),
                }
                for r in rows
            ],
            ["trace_id", "root", "spans", "duration_ms", "processes"],
        )
        return
    spans = state_api.get_trace(args.trace_id)
    if not spans:
        raise SystemExit(f"no trace {args.trace_id!r} (evicted, or never "
                         "sampled — set RAY_TPU_TRACE_SAMPLE/RAY_TPU_TRACING)")
    analysis = analyze_trace(spans)
    if args.format == "json":
        print(json.dumps({"analysis": analysis, "spans": spans},
                         indent=2, default=str))
        return
    t0 = min(s["start"] for s in spans)
    _print_table(
        [
            {
                "at_ms": f"{1000 * (s['start'] - t0):.2f}",
                "dur_ms": f"{1000 * (s['end'] - s['start']):.2f}",
                "name": s.get("name", ""),
                "stage": (s.get("attrs") or {}).get("stage", ""),
                "where": f"{s.get('node_id', '')}/pid={s.get('pid', '')}",
                "span": s.get("span_id", ""),
                "parent": s.get("parent_id") or "",
            }
            for s in sorted(spans, key=lambda s: s["start"])
        ],
        ["at_ms", "dur_ms", "name", "stage", "where", "span", "parent"],
    )
    print(f"\nend-to-end: {1000 * analysis['end_to_end_s']:.2f} ms over "
          f"{len(analysis['processes'])} processes "
          f"({', '.join(analysis['processes'])})")
    print("critical path:")
    for stage, d in analysis["stages"].items():
        print(f"  {stage:<14} {1000 * d['dur_s']:>9.2f} ms  "
              f"{100 * d['share']:5.1f}%")
    print(f"  {'(untracked)':<14} {1000 * analysis['untracked_s']:>9.2f} ms")
    if analysis["dominant_stage"]:
        print(f"dominant stage: {analysis['dominant_stage']}")


def cmd_chaos(args) -> None:
    """Fault-injection plane: the active chaos plan, per-fault trigger
    counts, and recent fault events (chaos.py; RAY_TPU_CHAOS_PLAN)."""
    from ray_tpu.util import state as state_api

    _connect(args)
    rows = state_api.list_chaos()
    if args.format == "json":
        print(json.dumps(rows, indent=2, default=str))
        return
    plan_rows = [r for r in rows if "plan" in r]
    events = [r for r in rows if "kind" in r]
    if not plan_rows:
        print("no chaos plan active (set RAY_TPU_CHAOS_PLAN on the head)")
    for r in plan_rows:
        print(f"plan: {r['plan']}")
        print(f"seed: {r['seed']}  armed: {r['armed']}  "
              f"elapsed: {r.get('elapsed_s', 0):.1f}s")
        counts = r.get("counts") or {}
        if counts:
            print("trigger counts:")
            for k in sorted(counts):
                print(f"  {k:<18} {counts[k]}")
        pend = r.get("pending_timed") or []
        if pend:
            print("pending timed faults:")
            for f in pend:
                print(f"  {f['kind']}@{f['at_s']}s "
                      f"({f['fired']}/{f['count']} fired)")
        parts = r.get("partitions") or {}
        if parts:
            print(f"partitions: {parts}")
    if events:
        print("\nrecent fault events:")
        _print_table(
            [
                {
                    "seq": e.get("seq", ""),
                    "kind": e.get("kind", ""),
                    "detail": " ".join(
                        f"{k}={v}" for k, v in e.items()
                        if k not in ("seq", "ts", "kind")
                    )[:100],
                }
                for e in events[-30:]
            ],
            ["seq", "kind", "detail"],
        )


def cmd_jobs(args) -> None:
    """Multi-tenant scheduler view: per-tenant usage vs quota plus the
    registered job table (fairsched). Quota units are hub resource
    units — whole TPU chips, CPU cores, 'memory' bytes."""
    from ray_tpu.util import state as state_api

    _connect(args)
    tenants = state_api.list_tenants()
    jobs = state_api.list_jobs()
    if args.format == "json":
        print(json.dumps({"tenants": tenants, "jobs": jobs}, indent=2,
                         default=str))
        return

    def _res(d):
        return ",".join(f"{k}={v:g}" for k, v in sorted(d.items())) or "-"

    print("tenants:")
    _print_table(
        [
            {
                "tenant": t["tenant"],
                "quota": _res(t.get("quota", {})),
                "in_use": _res(t.get("admitted", {})),
                "share": f"{t.get('share', 0.0):.2f}",
                "usage_s": f"{t.get('usage_s', 0.0):.1f}",
                "pending_quota": t.get("pending_quota", 0),
                "preempted": t.get("preempted", 0),
            }
            for t in tenants
        ],
        ["tenant", "quota", "in_use", "share", "usage_s",
         "pending_quota", "preempted"],
    )
    print("\njobs:")
    _print_table(
        [
            {
                "job_id": j["job_id"],
                "tenant": j["tenant"],
                "priority": j["priority"],
                "quota": _res(j.get("quota", {})),
                "submitted": j.get("submitted", 0),
                "dispatched": j.get("dispatched", 0),
                "preempted": j.get("preempted", 0),
            }
            for j in jobs
        ],
        ["job_id", "tenant", "priority", "quota", "submitted",
         "dispatched", "preempted"],
    )


def _parse_quota(spec: Optional[str]) -> dict:
    """'TPU=4,CPU=8' -> {'TPU': 4.0, 'CPU': 8.0} (also accepts JSON)."""
    if not spec:
        return {}
    spec = spec.strip()
    bad = SystemExit(
        f"--quota: expected RESOURCE=AMOUNT[,...] or a JSON object, "
        f"got {spec!r}"
    )
    if spec.startswith("{"):
        try:
            return {str(k): float(v) for k, v in json.loads(spec).items()}
        except (ValueError, TypeError, AttributeError):
            raise bad from None
    out = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        k, eq, v = part.partition("=")
        if not eq or not k.strip():
            raise bad
        try:
            out[k.strip()] = float(v)
        except ValueError:
            raise bad from None
    return out


def cmd_timeline(args) -> None:
    import ray_tpu

    _connect(args)
    events = ray_tpu.timeline()
    out = args.output or "ray_tpu_timeline.json"
    with open(out, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} events to {out} (chrome://tracing format)")


def cmd_memory(args) -> None:
    """Object-store view with leak attribution: one row per object
    (owner process, age, size, pins) plus the aggregate summary.
    --leak-suspects keeps only ready objects whose owner is GONE and
    that nothing pins — refs no live process can ever release."""
    from ray_tpu.util import state as state_api

    _connect(args)
    objects = state_api.list_objects()
    if args.leak_suspects:
        objects = state_api.leak_suspects(
            min_age_s=args.min_age, objects=objects
        )
    if args.format == "json":
        print(json.dumps(
            {"objects": objects,
             "summary": state_api.summarize_objects()},
            indent=2, default=str,
        ))
        return
    rows = [
        {
            "object_id": o.get("object_id", "")[:16],
            "kind": o.get("kind", ""),
            "size": o.get("size", 0),
            "owner": o.get("owner") or "?",
            "alive": "yes" if o.get("owner_alive", True) else "NO",
            "age_s": f"{o.get('age_s', 0.0):.1f}",
            "pins": o.get("pins", 0),
            "ready": o.get("ready"),
            "spilled": o.get("spilled"),
        }
        for o in sorted(
            objects, key=lambda o: o.get("age_s", 0.0), reverse=True
        )
    ]
    _print_table(rows, ["object_id", "kind", "size", "owner", "alive",
                        "age_s", "pins", "ready", "spilled"])
    summary = state_api.summarize_objects()
    print(
        f"\n{summary['ready']}/{summary['total']} ready, "
        f"{summary['total_size_bytes']} bytes, "
        f"{summary['spilled']} spilled, "
        f"{summary['leak_suspects']} leak suspect(s)"
    )


def cmd_stack(args) -> None:
    """On-demand all-thread stack dump of the hub or a worker — the
    profiler does not need to be on (reference: `ray stack`)."""
    from ray_tpu.util import profiler as prof_api

    _connect(args)
    reply = prof_api.stack(args.target, timeout=args.timeout)
    sys.stdout.write(prof_api.format_stack(reply))
    if reply.get("error"):
        raise SystemExit(1)


def cmd_profile(args) -> None:
    """Window the cluster-wide sampling profiler over --duration
    seconds and report: a stage/task/thread top table, and/or the raw
    flamegraph collapsed stacks (--fold FILE, '-' for stdout)."""
    from ray_tpu.util import profiler as prof_api

    _connect(args)
    print(f"profiling for {args.duration:.1f}s ...", file=sys.stderr)
    rows = prof_api.profile(args.duration)
    samples = [r for r in rows if not r.get("proc")]
    procs = prof_api.overhead(rows)
    if not samples:
        print(
            "no samples collected. Is the profiler on? Start the "
            "cluster with RAY_TPU_PROFILE_HZ=<rate> (e.g. 50) — the "
            "sampler is off by default.",
            file=sys.stderr,
        )
        raise SystemExit(1)
    if args.fold:
        lines = prof_api.fold_lines(samples)
        if args.fold == "-":
            sys.stdout.write("\n".join(lines) + "\n")
        else:
            with open(args.fold, "w") as f:
                f.write("\n".join(lines) + "\n")
            print(f"wrote {len(lines)} folded stacks to {args.fold}")
    if args.top or not args.fold:
        by = args.top or "stage"
        total = sum(r.get("samples", 0) for r in samples)
        print(f"\n{total} samples by {by}:")
        _print_table(
            [
                dict(r, share=f"{r['share'] * 100:.1f}%")
                for r in prof_api.top(samples, by=by, n=args.limit)
            ],
            [by, "samples", "share"],
        )
    if procs:
        print("\nsamplers:")
        _print_table(
            [
                {
                    "pid": m.get("pid"), "kind": m.get("kind"),
                    "hz": m.get("hz"),
                    "overhead": f"{m.get('overhead', 0.0) * 100:.2f}%",
                    "drops": m.get("drops", 0),
                }
                for m in procs
            ],
            ["pid", "kind", "hz", "overhead", "drops"],
        )


def cmd_job(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient

    addr = _resolve_address(args.address)
    if addr is None:
        # without this guard JobSubmissionClient would silently boot a
        # throwaway in-process cluster that dies when the CLI exits
        raise SystemExit(
            "no cluster address: pass --address, set RAY_TPU_ADDRESS, or "
            "run `python -m ray_tpu start --head` first"
        )
    client = JobSubmissionClient(address=addr)
    if args.job_cmd == "submit":
        import shlex

        # shlex.join: argv elements with spaces/parens must survive the
        # shell the job supervisor execs the entrypoint with
        entrypoint = shlex.join(args.entrypoint)
        if not entrypoint:
            raise SystemExit("job submit: pass the entrypoint after --")
        job_id = client.submit_job(
            entrypoint=entrypoint,
            tenant=args.tenant,
            priority=args.priority,
            # tri-state: omitted --quota keeps the tenant's cap;
            # an explicit empty spec ("{}") lifts it
            quota=_parse_quota(args.quota) if args.quota is not None
            else None,
        )
        print(job_id)
        if args.wait:
            status = client.wait_until_finished(job_id, timeout=args.timeout)
            print(status)
            sys.stdout.write(client.get_job_logs(job_id))
            if status != "SUCCEEDED":
                raise SystemExit(1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        sys.stdout.write(client.get_job_logs(args.job_id))
    elif args.job_cmd == "list":
        _print_table(
            client.list_jobs(), ["submission_id", "status", "entrypoint"]
        )
    elif args.job_cmd == "stop":
        print(client.stop_job(args.job_id))


def cmd_serve(args) -> None:
    """Serve-plane SLO status: one row per (deployment, route) with
    request/error/timeout counts, latency percentiles estimated from
    the hub's histogram buckets, live load gauges, batch efficiency,
    the drain-vs-drop teardown counters, and the overload/resilience
    counters (shed admissions, expired deadlines, replica ejections)."""
    from ray_tpu.util import state as state_api

    _connect(args)
    summary = state_api.summarize_serve()
    if args.format == "json":
        print(json.dumps(summary, indent=2, default=str))
        return

    def _ms(v):
        return f"{v * 1000:.1f}" if v is not None else "-"

    rows = []
    for name, dep in sorted(summary["deployments"].items()):
        for route, r in sorted(dep["routes"].items()):
            lat = r["latency_s"] or {}
            rows.append({
                "deployment": name,
                "route": route or "-",
                "requests": r["requests"],
                "errors": r["errors"],
                "timeouts": r["timeouts"],
                "p50_ms": _ms(lat.get("p50")),
                "p95_ms": _ms(lat.get("p95")),
                "p99_ms": _ms(lat.get("p99")),
                "replicas": dep["replicas"],
                "ongoing": dep["ongoing"],
                "queued": dep["queued"],
                "batch_eff": (
                    f"{dep['batch_efficiency']:.2f}"
                    if dep["batch_efficiency"] is not None
                    else "-"
                ),
                "drained": dep["drained"],
                "dropped": dep["dropped"],
                "shed": dep.get("shed", 0),
                "expired": dep.get("expired", 0),
                "ejections": dep.get("ejections", 0),
            })
    if not rows:
        print("no serve metrics recorded (is a deployment running?)")
        return
    _print_table(rows, [
        "deployment", "route", "requests", "errors", "timeouts",
        "p50_ms", "p95_ms", "p99_ms", "replicas", "ongoing", "queued",
        "batch_eff", "drained", "dropped", "shed", "expired", "ejections",
    ])


def cmd_debug(args) -> None:
    from ray_tpu.util import rpdb

    _connect(args)
    bps = rpdb.list_breakpoints()
    if not bps:
        print("no active breakpoints")
        return
    for i, bp in enumerate(bps):
        print(f"[{i}] {bp['uuid']} pid={bp['pid']} {bp['host']}:{bp['port']}")
    choice = 0
    if len(bps) > 1 and sys.stdin.isatty():
        choice = int(input("attach to which breakpoint? ") or "0")
    print(f"attaching to {bps[choice]['uuid']} (Ctrl-D to detach)")
    rpdb.connect(bps[choice]["uuid"])


# ------------------------------------------------------------------ parser
def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    def add_address(sp):
        sp.add_argument("--address", default=None, help="tcp://host:port")

    sp = sub.add_parser("start", help="start a head or join as a node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--host", default="0.0.0.0")
    sp.add_argument("--port", type=int, default=0,
                    help="head listen port (0 = ephemeral)")
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.add_argument("--num-tpus", type=int, default=None)
    sp.add_argument("--max-workers", type=int, default=None)
    sp.add_argument("--hub-shards", type=int, default=None,
                    help="reactor shard count for the head's control "
                         "plane (0/unset = auto: min(4, cpu count); "
                         "1 = single-reactor)")
    sp.add_argument("--node-id", default=None)
    add_address(sp)
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the head started by this CLI")
    add_address(sp)
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster nodes + resources")
    add_address(sp)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument(
        "kind",
        choices=["actors", "tasks", "workers", "nodes", "objects",
                 "placement_groups", "pgs", "jobs", "tenants", "shards",
                 "traces", "chaos", "profile"],
    )
    sp.add_argument("--format", choices=["table", "json"], default="table")
    add_address(sp)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary", help="aggregate state summaries")
    sp.add_argument("kind", choices=["tasks", "actors", "objects"])
    add_address(sp)
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("events", help="flight-recorder runtime events")
    sp.add_argument("--kind", default=None,
                    help="filter by event kind (e.g. node_down)")
    sp.add_argument("--format", choices=["table", "json"], default="table")
    add_address(sp)
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser(
        "jobs", help="multi-tenant scheduler: tenants (usage vs quota) "
                     "+ registered jobs"
    )
    sp.add_argument("--format", choices=["table", "json"], default="table")
    add_address(sp)
    sp.set_defaults(fn=cmd_jobs)

    sp = sub.add_parser(
        "chaos", help="fault-injection plane: active plan, trigger "
                      "counts, recent fault events"
    )
    sp.add_argument("--format", choices=["table", "json"], default="table")
    add_address(sp)
    sp.set_defaults(fn=cmd_chaos)

    sp = sub.add_parser(
        "trace", help="distributed runtime traces: list, or one trace's "
                      "spans + critical-path breakdown"
    )
    sp.add_argument("trace_id", nargs="?", default=None)
    sp.add_argument("--format", choices=["table", "json"], default="table")
    add_address(sp)
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("timeline", help="dump chrome://tracing timeline")
    sp.add_argument("--output", default=None)
    add_address(sp)
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "memory", help="object store: per-object owner/age/size rows "
                       "+ leak suspects"
    )
    sp.add_argument("--leak-suspects", action="store_true",
                    help="only ready objects whose owner process is "
                         "gone and that no in-flight task pins")
    sp.add_argument("--min-age", type=float, default=60.0,
                    help="leak-suspect age floor in seconds")
    sp.add_argument("--format", choices=["table", "json"], default="table")
    add_address(sp)
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser(
        "stack", help="all-thread stack dump of the hub or a worker "
                      "(no profiler needed)"
    )
    sp.add_argument("target", nargs="?", default="hub",
                    help='"hub" (default), a worker id (prefix ok), '
                         "or a worker pid")
    sp.add_argument("--timeout", type=float, default=10.0)
    add_address(sp)
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser(
        "profile", help="sample the cluster for N seconds and report "
                        "folded stacks / stage tops (needs "
                        "RAY_TPU_PROFILE_HZ > 0)"
    )
    sp.add_argument("--duration", type=float, default=5.0)
    sp.add_argument("--fold", default=None, metavar="FILE",
                    help="write flamegraph collapsed stacks ('-' = "
                         "stdout)")
    sp.add_argument("--top", default=None,
                    choices=["stage", "task", "thread", "kind", "stack"],
                    help="aggregate table dimension (default: stage "
                         "when --fold is not given)")
    sp.add_argument("--limit", type=int, default=20,
                    help="top-table row cap")
    add_address(sp)
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("job", help="job submission")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=600.0)
    j.add_argument("--tenant", default=None,
                   help="fairsched tenant the job's work is accounted to")
    j.add_argument("--priority", type=int, default=None,
                   help="integer scheduling priority (higher wins)")
    j.add_argument("--quota", default=None,
                   help='resource quota, "TPU=4,CPU=8" or JSON')
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    add_address(j)
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("job_id")
        add_address(j)
    j = jsub.add_parser("list")
    add_address(j)
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser(
        "serve", help="serve-plane SLOs: per-deployment/per-route "
                      "request counts, latency percentiles, batch "
                      "efficiency"
    )
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    s = ssub.add_parser("status", help="per-deployment SLO table")
    s.add_argument("--format", choices=["table", "json"], default="table")
    add_address(s)
    s.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("debug", help="attach to a remote breakpoint")
    add_address(sp)
    sp.set_defaults(fn=cmd_debug)

    return p


def main(argv: Optional[List[str]] = None) -> None:
    args = _build_parser().parse_args(argv)
    # strip a leading "--" from REMAINDER entrypoints
    if getattr(args, "entrypoint", None) and args.entrypoint[0] == "--":
        args.entrypoint = args.entrypoint[1:]
    args.fn(args)


if __name__ == "__main__":
    main()
