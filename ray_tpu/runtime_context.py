"""ray_tpu.get_runtime_context(): where am I running?

Parity: python/ray/runtime_context.py (`ray.get_runtime_context()` —
get_node_id/get_job_id/get_worker_id/get_task_id/get_actor_id,
accelerator ids). Identity comes from the process's CoreClient; the
current task/actor ids are contextvars set by the worker executor
around every user-code invocation, so nested helper calls and async
actor methods all see the right ids.
"""

from __future__ import annotations

import contextvars
import os
from typing import List, Optional

_current_task_id: contextvars.ContextVar[Optional[bytes]] = (
    contextvars.ContextVar("ray_tpu_task_id", default=None)
)
# (pg_id bytes, bundle_idx) of the currently-executing task, or None;
# set by the worker executor, read by get_current_placement_group()
_current_pg: contextvars.ContextVar[Optional[tuple]] = (
    contextvars.ContextVar("ray_tpu_current_pg", default=None)
)


class RuntimeContext:
    def get_node_id(self) -> str:
        from ._private import worker

        if worker.is_initialized():
            return worker.get_client().node_id
        return os.environ.get("RAY_TPU_NODE_ID", "node0")

    def get_worker_id(self) -> str:
        from ._private import worker

        if worker.is_initialized():
            return worker.get_client().worker_id
        return "driver"

    def get_job_id(self) -> str:
        # one hub session = one job in this runtime's model
        return os.environ.get("RAY_TPU_JOB_ID", "job0")

    def get_task_id(self) -> Optional[str]:
        """Hex id of the currently-executing task (None on the driver)."""
        tid = _current_task_id.get()
        return tid.hex() if tid is not None else None

    def get_actor_id(self) -> Optional[str]:
        """Hex id of the current actor (None outside an actor)."""
        from ._private import worker

        runtime = getattr(worker, "_worker_runtime", None)
        if runtime is not None and runtime.actor_id is not None:
            return runtime.actor_id.hex()
        return None

    def get_accelerator_ids(self) -> dict:
        """Visible accelerator ids (reference: TPU_VISIBLE_CHIPS)."""
        chips = os.environ.get("TPU_VISIBLE_CHIPS", "")
        return {"TPU": [c for c in chips.split(",") if c]}

    @property
    def was_current_actor_reconstructed(self) -> bool:
        # per-runtime flag, not os.environ: a process-wide env var would
        # leak one actor's restart marker to later actors hosted by the
        # same worker
        from ._private import worker

        runtime = getattr(worker, "_worker_runtime", None)
        if runtime is not None:
            return bool(getattr(runtime, "actor_restarted", False))
        return False


_context = RuntimeContext()


def get_runtime_context() -> RuntimeContext:
    return _context
