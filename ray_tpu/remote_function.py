"""RemoteFunction: the object `@remote` turns a function into.

Parity: python/ray/remote_function.py:41 in the reference. The function
is cloudpickled once per process and exported to the hub's function
table keyed by a content digest (the reference exports via GCS KV,
python/ray/_private/function_manager.py:196); workers fetch + cache.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

import pickle

from ._private.object_store import INLINE_THRESHOLD
from ._private.serialization import (
    MARKER_PLAIN,
    PICKLE5,
    dumps_function,
    dumps_inline,
)
from .object_ref import ObjectRef

# encode_args fast path: exact types that can't need spilling (beyond
# the blob-size check), carry no ObjectRef deps, and pickle identically
# under stdlib pickle and cloudpickle — no by-reference trap, so the
# cloudpickle encoder (~5x slower, pure python) can be skipped
_INLINE_FAST_TYPES = frozenset((int, float, bool, str, bytes, type(None)))

# Options accepted by @remote / .options() — superset kept aligned with
# the reference's ray_option_utils.py validation table.
_TASK_OPTION_KEYS = {
    "num_cpus",
    "num_gpus",
    "num_tpus",
    "resources",
    "num_returns",
    "max_retries",
    "retry_exceptions",
    "name",
    "scheduling_strategy",
    "runtime_env",
    "memory",
    "max_calls",
    "priority",
    "tenant",
    "timeout_s",
    "_metadata",
}


def canonical_resources(opts: Dict[str, Any], is_actor: bool) -> Dict[str, float]:
    res: Dict[str, float] = {}
    ncpu = opts.get("num_cpus")
    if ncpu is None:
        ncpu = 0 if is_actor else 1
    if ncpu:
        res["CPU"] = float(ncpu)
    if opts.get("num_gpus"):
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    for k, v in (opts.get("resources") or {}).items():
        res[k] = float(v)
    return res


def encode_args(client, args: tuple, kwargs: dict):
    """Encode call args: spill large ndarray/bytes args to the object store,
    collect top-level ObjectRef dependencies, inline the rest.

    Mirrors the reference's arg handling: small args inline with the task
    spec, large args become owned objects passed by reference
    (python/ray/_raylet.pyx prepare_args). Returns
    (args_kind, payload, deps, holds): `holds` are owned twin refs for
    the spilled objects — the caller attaches them to the task's return
    refs so spilled args are freed when the call's results are dropped
    (the hub pins them while the task is in flight), instead of leaking
    one shm segment per call."""
    if not kwargs:
        # all-primitive positional call (the .remote() hot-path shape):
        # nothing can be an ObjectRef or ndarray, so skip the spill
        # scan, and stdlib pickle's C encoder replaces cloudpickle.
        # Plain loop, not all(genexpr) — this runs per .remote() call.
        for a in args:
            if type(a) not in _INLINE_FAST_TYPES:
                break
        else:
            blob = MARKER_PLAIN + pickle.dumps((args, kwargs), PICKLE5)
            if len(blob) <= INLINE_THRESHOLD:
                return "inline", blob, [], []
            # an oversized str/bytes arg still spills — fall through
    import numpy as np

    deps: List[bytes] = []
    holds: List[ObjectRef] = []

    def spill(v):
        if isinstance(v, ObjectRef):
            deps.append(v._id.binary())
            return v
        big = False
        if isinstance(v, np.ndarray) and v.nbytes > INLINE_THRESHOLD:
            big = True
        elif isinstance(v, (bytes, bytearray)) and len(v) > INLINE_THRESHOLD:
            big = True
        if big:
            oid = client.put_value(v)
            deps.append(oid.binary())
            holds.append(ObjectRef(oid, _owned=True))
            # the pickled copy is a plain (non-owned) ref; the owned
            # twin above stays unpickled so ownership GC can fire
            return ObjectRef(oid)
        return v

    args = tuple(spill(a) for a in args)
    kwargs = {k: spill(v) for k, v in kwargs.items()}
    blob = dumps_inline((args, kwargs))
    if len(blob) > INLINE_THRESHOLD:
        oid = client.put_value((args, kwargs))
        deps.append(oid.binary())
        holds.append(ObjectRef(oid, _owned=True))
        return "ref", oid.binary(), deps, holds
    return "inline", blob, deps, holds


def scheduling_options(opts: Dict[str, Any]) -> Dict[str, Any]:
    """Extract hub-visible scheduling options (placement group etc.)."""
    out: Dict[str, Any] = {}
    strategy = opts.get("scheduling_strategy")
    if strategy is not None:
        from .util.scheduling_strategies import PlacementGroupSchedulingStrategy

        from .util.scheduling_strategies import NodeAffinitySchedulingStrategy

        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg = strategy.placement_group
            out["placement_group"] = (pg.id.binary(), strategy.placement_group_bundle_index)
        elif isinstance(strategy, NodeAffinitySchedulingStrategy):
            out["node_affinity"] = (strategy.node_id, strategy.soft)
        elif isinstance(strategy, str):
            out["strategy"] = strategy
    if opts.get("max_retries") is not None:
        out["max_retries"] = opts["max_retries"]
    if opts.get("timeout_s"):
        # execute deadline: past it the hub SIGKILLs the (possibly
        # hung) worker and retries the task against its crash budget,
        # failing with TaskTimeoutError once exhausted
        out["timeout_s"] = float(opts["timeout_s"])
    # multi-tenant scheduling (fairsched): per-call priority/tenant
    # override the driver's registered JobConfig (client._stamp_job
    # fills the defaults with setdefault, so explicit values win)
    if opts.get("priority") is not None:
        out["priority"] = int(opts["priority"])
    if opts.get("tenant"):
        out["tenant"] = str(opts["tenant"])
    if opts.get("retry_exceptions"):
        # True = retry any application error; exception type(s) retry
        # only matching errors (reference: ray_option_utils semantics).
        # Class objects must not ride the plain-pickle frame codec raw —
        # a __main__-defined exception class pickles by reference and
        # fails to resolve in a remote hub — so anything non-bool ships
        # as a cloudpickle blob (hub._maybe_retry_app_error unwraps it).
        rex = opts["retry_exceptions"]
        if not isinstance(rex, bool):
            rex = _retry_exceptions_blob(rex)
        out["retry_exceptions"] = rex
    return out


# retry_exceptions blob memo: the class list is static per decoration,
# but scheduling_options runs per .remote() call — without the memo
# every submit would pay a CloudPickler round (by-value for __main__
# classes) on the hot path. Keyed by the class tuple itself.
_REX_BLOB_MEMO: Dict[tuple, bytes] = {}


def _retry_exceptions_blob(rex) -> bytes:
    classes = tuple(rex) if isinstance(rex, (list, tuple)) else (rex,)
    blob = _REX_BLOB_MEMO.get(classes)
    if blob is None:
        if len(_REX_BLOB_MEMO) > 256:
            _REX_BLOB_MEMO.clear()
        blob = _REX_BLOB_MEMO[classes] = dumps_inline(classes)
    return blob


def _uploaded_env_uris(client) -> set:
    """Per-CLIENT memo of wheel URIs already uploaded (content-hashed,
    one upload serves every later submit). Keyed on the client object:
    a new cluster connection starts empty, so a fresh hub's KV gets the
    wheels again."""
    memo = getattr(client, "_env_upload_memo", None)
    if memo is None:
        memo = client._env_upload_memo = set()
    return memo


def process_runtime_env(client, opts: Dict[str, Any], out: Dict[str, Any]) -> None:
    """Package a runtime_env for the hub (reference: the runtime-env
    agent's URI flow, _private/runtime_env/agent/runtime_env_agent.py:167
    + working_dir plugin): env_vars travel inline; working_dir is zipped
    once per content hash into the cluster KV (the GCS-KV upload path)
    and workers materialize it from the URI with local caching."""
    renv = opts.get("runtime_env")
    if not renv:
        return
    import hashlib
    import io
    import json
    import os
    import zipfile

    processed: Dict[str, Any] = {}
    if renv.get("env_vars"):
        processed["env_vars"] = {
            str(k): str(v) for k, v in renv["env_vars"].items()
        }
    wd = renv.get("working_dir")
    if wd:
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _, files in os.walk(wd):
                for fname in sorted(files):
                    full = os.path.join(root, fname)
                    zf.write(full, os.path.relpath(full, wd))
        blob = buf.getvalue()
        uri = hashlib.sha1(blob).hexdigest()[:16]
        client.kv_put(f"__runtime_env_pkg__{uri}".encode(), blob,
                      overwrite=True)
        processed["working_dir_uri"] = uri
    if renv.get("pip") is not None and renv.get("uv") is not None:
        raise ValueError(
            "runtime_env accepts 'pip' OR 'uv', not both"
        )
    pip = renv.get("pip") if renv.get("pip") is not None else renv.get("uv")
    if pip:
        # reference: _private/runtime_env/pip.py / uv.py — requirements
        # materialize node-side into a cached env dir. Local wheel/sdist
        # paths upload once (content-hash URI) into the cluster KV so
        # every node can install them offline; plain requirement strings
        # pass through (they need an index reachable from the nodes).
        if isinstance(pip, dict):
            pip = pip.get("packages", [])
        if isinstance(pip, str):
            # reference form: a requirements.txt path (runtime_env pip
            # accepts the file path directly)
            path = os.path.expanduser(pip)
            if os.path.isfile(path):
                with open(path) as f:
                    pip = [
                        ln.strip() for ln in f
                        if ln.strip() and not ln.strip().startswith("#")
                    ]
            else:
                pip = [pip]
        reqs: list = []
        wheels: Dict[str, str] = {}  # content uri -> original filename
        memo = _uploaded_env_uris(client)
        for r in pip:
            r = str(r)
            path = os.path.expanduser(r)
            if os.path.isfile(path) and path.endswith(
                (".tar.gz", ".zip")
            ):
                # sdists need a build backend (setuptools) pip would
                # fetch from an index — impossible on egress-less nodes
                raise ValueError(
                    f"runtime_env pip: ship built wheels, not sdists "
                    f"({r}); run `pip wheel {r}` first"
                )
            if os.path.isfile(path) and path.endswith(".whl"):
                with open(path, "rb") as f:
                    blob = f.read()
                uri = hashlib.sha1(blob).hexdigest()[:16]
                if uri not in memo:
                    # upload once per client; the KV keeps it for nodes
                    client.kv_put(f"__runtime_env_whl__{uri}".encode(),
                                  blob, overwrite=True)
                    memo.add(uri)
                wheels[uri] = os.path.basename(path)
            else:
                reqs.append(r)
        processed["pip"] = {"reqs": sorted(reqs),
                            "wheels": dict(sorted(wheels.items()))}
    mods = renv.get("py_modules")
    if mods:
        # reference: _private/runtime_env/py_modules.py — each entry is
        # a local package dir (zipped once per content hash into the
        # cluster KV, extracted onto sys.path node-side) or a built
        # wheel (rides the pip/offline-wheel machinery)
        mod_uris: list = []
        memo = _uploaded_env_uris(client)
        for m in mods:
            path = os.path.expanduser(str(m))
            if os.path.isfile(path) and path.endswith(".whl"):
                with open(path, "rb") as f:
                    blob = f.read()
                uri = hashlib.sha1(blob).hexdigest()[:16]
                if uri not in memo:
                    client.kv_put(f"__runtime_env_whl__{uri}".encode(),
                                  blob, overwrite=True)
                    memo.add(uri)
                pip_spec = processed.setdefault(
                    "pip", {"reqs": [], "wheels": {}}
                )
                pip_spec["wheels"][uri] = os.path.basename(path)
            elif os.path.isdir(path):
                buf = io.BytesIO()
                base = os.path.basename(path.rstrip(os.sep))
                with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
                    for root, _, files in os.walk(path):
                        for fname in sorted(files):
                            full = os.path.join(root, fname)
                            rel = os.path.join(
                                base, os.path.relpath(full, path)
                            )
                            zf.write(full, rel)
                blob = buf.getvalue()
                uri = hashlib.sha1(blob).hexdigest()[:16]
                if uri not in memo:  # upload once per client per content
                    client.kv_put(f"__runtime_env_pkg__{uri}".encode(),
                                  blob, overwrite=True)
                    memo.add(uri)
                mod_uris.append(uri)
            else:
                raise ValueError(
                    f"runtime_env py_modules entry {m!r} must be a local "
                    "package directory or a built wheel"
                )
        if mod_uris:
            processed["py_modules"] = mod_uris
    conda = renv.get("conda")
    if conda is not None:
        # reference: _private/runtime_env/conda.py — a named env or an
        # environment.yml-style dict; materialization happens node-side
        # (hash-cached, file-locked) and the worker re-execs inside the
        # env's interpreter
        if isinstance(conda, str):
            processed["conda"] = {"name": conda}
        elif isinstance(conda, dict):
            processed["conda"] = {
                "spec": json.loads(json.dumps(conda, sort_keys=True))
            }
        else:
            raise ValueError(
                "runtime_env conda must be an env name or an "
                "environment dict"
            )
    unknown = set(renv) - {
        "env_vars", "working_dir", "pip", "uv", "py_modules", "conda",
    }
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)} (supported: "
            "env_vars, working_dir, pip, uv, py_modules, conda; "
            "'container' needs a container runtime this environment "
            "does not ship)"
        )
    out["runtime_env"] = processed
    out["runtime_env_hash"] = hashlib.sha1(
        json.dumps(processed, sort_keys=True).encode()
    ).hexdigest()[:16]


class _SubmitTemplate:
    """The invariant half of this function's submit payload, computed
    once per (RemoteFunction, client generation) instead of per call:
    fn export, canonical resources, scheduling options (including the
    runtime_env packaging, which may upload wheels/zips), and the
    max_retries default. Per call only the args/ids re-encode; callers
    shallow-copy ``options`` before submitting because the client's
    job stamp (setdefault) and the hub mutate options in place.

    ``splice`` extends the template to raw bytes: (job-identity tuple,
    frame prefix) — the invariant fields of a SUBMIT_TASKS frame
    pickled ONCE (serialization.submit_frame_prefix) with the job
    stamp baked in, so a plain ``.remote()`` call splices only its
    per-call fragment (client.submit_batched). Rebuilt when the
    identity changes; ``splice_broken`` latches a template whose
    options defeat splicing (memo-reading pickle) onto the classic
    per-call path permanently."""

    __slots__ = ("fn_id", "num_returns", "resources", "options",
                 "splice", "splice_broken")


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        self._fn_blob = None
        self._fn_id: Optional[str] = None
        # registration memo: client.client_epoch at last export. A
        # reconnect (shutdown + re-init) builds a NEW CoreClient with a
        # fresh epoch, so the steady-state "is it exported?" check is
        # one int compare with natural invalidation.
        self._export_epoch = 0
        self._tpl: Optional[_SubmitTemplate] = None
        self._tpl_epoch = 0
        # .options() variants keep the classic unbatched frame: the
        # override is the caller saying "this call is different" —
        # auto-batching stays reserved for the plain decorated function
        self._variant = False
        self.__name__ = getattr(fn, "__name__", "remote_fn")
        self.__doc__ = getattr(fn, "__doc__", None)

    def _ensure_exported(self, client) -> str:
        if self._export_epoch == getattr(client, "client_epoch", None):
            return self._fn_id
        if self._fn_blob is None:
            self._fn_blob = dumps_function(self._fn)
            digest = hashlib.sha1(self._fn_blob).hexdigest()[:16]
            self._fn_id = f"{self.__name__}:{digest}"
        client.register_function(self._fn_id, self._fn_blob)
        self._export_epoch = getattr(client, "client_epoch", None)
        return self._fn_id

    def _template(self, client) -> _SubmitTemplate:
        tpl = self._tpl
        if tpl is not None and self._tpl_epoch == client.client_epoch:
            return tpl
        opts = self._options
        tpl = _SubmitTemplate()
        tpl.fn_id = self._ensure_exported(client)
        tpl.num_returns = opts.get("num_returns", 1)
        tpl.resources = canonical_resources(opts, is_actor=False)
        options = scheduling_options(opts)
        process_runtime_env(client, opts, options)
        options.setdefault("max_retries", opts.get("max_retries", 3))
        tpl.options = options
        tpl.splice = None
        tpl.splice_broken = False
        self._tpl = tpl
        self._tpl_epoch = client.client_epoch
        return tpl

    def _splice_prefix(self, client, tpl: _SubmitTemplate):
        """The template's (frame prefix, classic-payload base) for the
        CURRENT job identity (cached on the template; one slot —
        identity changes mid-process are worker-side rarities, not a
        hot path). The base dict carries the same stamped invariant
        fields as the prefix so a singleton drain can fall back to the
        classic SUBMIT_TASK frame without re-stamping. None = this
        template cannot splice; the caller falls back to the classic
        frame and splice_broken stops re-trying."""
        ident = client._current_job_identity()
        cached = tpl.splice
        if cached is not None and cached[0] == ident:
            return cached[1], cached[2]
        from ._private import protocol as P
        from ._private.serialization import submit_frame_prefix

        stamped = dict(tpl.options)
        client._stamp_job(stamped)
        prefix = submit_frame_prefix(P.SUBMIT_TASKS, {
            "fn_id": tpl.fn_id,
            "resources": tpl.resources,
            "options": stamped,
            # strict .remote() placement semantics: auto-batched tasks
            # must not opt into bulk pipelining (hub _pipeline_ok)
            "pipeline": False,
        })
        if prefix is None:
            tpl.splice_broken = True
            return None
        base = {
            "fn_id": tpl.fn_id,
            "resources": tpl.resources,
            "options": stamped,
        }
        tpl.splice = (ident, prefix, base)
        return prefix, base

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(opts)
        rf = RemoteFunction(self._fn, merged)
        rf._fn_blob = self._fn_blob
        rf._fn_id = self._fn_id
        rf._variant = True
        return rf

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: ray.dag — fn.bind)."""
        from .dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def _remote(self, args, kwargs, opts):
        from ._private import worker

        client = worker.get_client()
        if opts.get("num_returns", 1) == "streaming":
            # streaming keeps the untemplated path: its options are
            # call-variant (forced max_retries=0, backpressure knobs)
            fn_id = self._ensure_exported(client)
            args_kind, args_payload, deps, holds = encode_args(
                client, args, kwargs)
            resources = canonical_resources(opts, is_actor=False)
            options = scheduling_options(opts)
            process_runtime_env(client, opts, options)
            from .object_ref import ObjectRefGenerator

            options["streaming"] = True
            if opts.get("_generator_backpressure_num_objects"):
                options["_generator_backpressure_num_objects"] = opts[
                    "_generator_backpressure_num_objects"
                ]
            # a partially-consumed stream cannot be transparently
            # re-executed; no retries (reference behaves likewise for
            # yielded-and-consumed prefixes)
            options["max_retries"] = 0
            task_id, _ = client.submit_task(
                fn_id, args_kind, args_payload, deps, 0, resources, options,
                return_task_id=True,
            )
            gen = ObjectRefGenerator(task_id)
            gen._hold = holds or None
            return gen
        tpl = self._template(client)
        args_kind, args_payload, deps, holds = encode_args(
            client, args, kwargs)
        # transparent auto-batching: a plain single-return call with a
        # spliceable template rides the bulk ABI through the client's
        # window. num_returns/options() overrides, window=0, broken
        # splices, and per-call head-sampled tracing (no ambient
        # context to key the batch on) all keep the classic frame.
        if (tpl.num_returns == 1 and not self._variant
                and not tpl.splice_broken and client._ab_window_s > 0.0):
            trace_ctx = None
            batchable = True
            if client._tracing_live():
                trace_ctx = client._trace_ctx()
                if trace_ctx is None:
                    batchable = False
            if batchable:
                spl = self._splice_prefix(client, tpl)
                if spl is not None:
                    from ._private.ids import ObjectID

                    rid = client.submit_batched(
                        spl[0], spl[1], args_kind, args_payload, deps,
                        trace_ctx)
                    ref = ObjectRef(ObjectID(rid), _owned=True)
                    if holds:
                        ref._hold = holds
                    return ref
        return_ids = client.submit_task(
            tpl.fn_id, args_kind, args_payload, deps, tpl.num_returns,
            tpl.resources, dict(tpl.options),
        )
        refs = [ObjectRef(r, _owned=True) for r in return_ids]
        if holds:
            for r in refs:
                r._hold = holds
        if tpl.num_returns == 1:
            return refs[0]
        return refs

    def map(self, items) -> list:
        """Submit one task per item in a SINGLE wire frame and return
        the ObjectRefs up front (vectorized fan-out; parity target:
        the Podracer-style thousands-of-homogeneous-tasks-per-step
        pattern). Each item supplies the call's positional arguments —
        a tuple is splatted (``f.map([(1, 2)])`` calls ``f(1, 2)``, so
        ``f.map([()] * n)`` makes n nullary calls), anything else is
        the single argument. Keyword arguments are not supported.

        Compared to ``[f.remote(x) for x in items]`` this encodes the
        shared fields once, draws every id from one entropy slab, and
        costs one frame + one hub admission pass instead of n — use it
        whenever the calls are homogeneous and the refs are needed
        together; use ``.remote`` when calls trickle in or vary in
        options."""
        from ._private import worker

        items = list(items)
        if not items:
            return []
        client = worker.get_client()
        tpl = self._template(client)
        if tpl.num_returns == "streaming":
            raise ValueError("map() does not support streaming tasks")
        encoded = []
        hold_rows = []
        for it in items:
            call_args = it if isinstance(it, tuple) else (it,)
            args_kind, args_payload, deps, holds = encode_args(
                client, call_args, {})
            encoded.append((args_kind, args_payload, deps))
            hold_rows.append(holds)
        _task_ids, rid_rows = client.submit_many(
            tpl.fn_id, encoded, tpl.num_returns, tpl.resources,
            dict(tpl.options),
        )
        from ._private.ids import ObjectID

        out = []
        for row, holds in zip(rid_rows, hold_rows):
            refs = [ObjectRef(ObjectID(r), _owned=True) for r in row]
            if holds:
                for ref in refs:
                    ref._hold = holds
            out.append(refs[0] if tpl.num_returns == 1 else refs)
        return out

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self.__name__}' cannot be called directly; "
            f"use '{self.__name__}.remote()'."
        )
