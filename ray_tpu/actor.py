"""Actors: stateful workers with ordered method-call semantics.

Parity: python/ray/actor.py in the reference (ActorClass :617,
ActorHandle :1287, ActorMethod :116). An actor pins a worker process for
its lifetime; calls are FIFO per-caller (ordered queue, reference:
src/ray/core_worker/transport/actor_task_submitter.h:78), optionally
concurrent via max_concurrency or asyncio for coroutine methods.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

from ._private.ids import ActorID
from ._private.serialization import dumps_function
from .object_ref import ObjectRef
from .remote_function import (
    canonical_resources,
    encode_args,
    process_runtime_env,
    scheduling_options,
)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, options: Optional[dict] = None):
        self._handle = handle
        self._name = name
        self._options = dict(options or {})

    def options(self, **opts) -> "ActorMethod":
        merged = dict(self._options)
        merged.update(opts)
        return ActorMethod(self._handle, self._name, merged)

    def remote(self, *args, **kwargs):
        from ._private import worker

        client = worker.get_client()
        args_kind, args_payload, deps, holds = encode_args(client, args, kwargs)
        # caller-supplied dependency pins (serve payload codec): ids of
        # objects referenced from INSIDE the args — e.g. payload markers
        # nested in handle_request's args tuple, which encode_args'
        # top-level scan can't see. Riding in arg_deps gets them the
        # same hub pin-while-in-flight protection spilled args have, so
        # a caller dropping its refs early can't free a payload the
        # replica hasn't fetched yet.
        extra_deps = self._options.get("_extra_arg_deps")
        if extra_deps:
            deps = deps + list(extra_deps)
        num_returns = self._options.get("num_returns", 1)
        options = scheduling_options(self._options)
        if num_returns == "streaming":
            from .object_ref import ObjectRefGenerator

            options["streaming"] = True
            if self._options.get("_generator_backpressure_num_objects"):
                options["_generator_backpressure_num_objects"] = self._options[
                    "_generator_backpressure_num_objects"
                ]
            task_id, _ = client.submit_actor_task(
                self._handle._actor_id,
                self._name,
                args_kind,
                args_payload,
                deps,
                0,
                options,
                return_task_id=True,
            )
            gen = ObjectRefGenerator(task_id)
            gen._hold = holds or None
            return gen
        return_ids = client.submit_actor_task(
            self._handle._actor_id,
            self._name,
            args_kind,
            args_payload,
            deps,
            num_returns,
            options,
        )
        refs = [ObjectRef(r, _owned=True) for r in return_ids]
        if holds:
            for r in refs:
                r._hold = holds
        return refs[0] if num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: ray.dag — actor.method.bind)."""
        from .dag.dag_node import ClassMethodNode

        return ClassMethodNode(self, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(f"Actor method '{self._name}' must be called with .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, ready_ref: Optional[ObjectRef] = None):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_ready_ref", ready_ref)

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("__") and name.endswith("__") and name != "__ray_call__":
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __ray_ready__(self) -> ObjectRef:
        return ActorMethod(self, "__ray_ready__").remote()

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id.binary(),))


def _rebuild_handle(actor_id_bytes: bytes) -> ActorHandle:
    return ActorHandle(ActorID(actor_id_bytes))


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        self._blob = None
        self._fn_id: Optional[str] = None
        self.__name__ = getattr(cls, "__name__", "Actor")
        self.__doc__ = getattr(cls, "__doc__", None)

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._options)
        merged.update(opts)
        ac = ActorClass(self._cls, merged)
        ac._blob = self._blob
        ac._fn_id = self._fn_id
        return ac

    def _ensure_exported(self, client) -> str:
        if self._blob is None:
            self._blob = dumps_function(self._cls)
            digest = hashlib.sha1(self._blob).hexdigest()[:16]
            self._fn_id = f"{self.__name__}:{digest}"
        client.register_function(self._fn_id, self._blob)
        return self._fn_id

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ._private import worker

        client = worker.get_client()
        opts = self._options
        fn_id = self._ensure_exported(client)
        args_kind, args_payload, deps, holds = encode_args(client, args, kwargs)
        resources = canonical_resources(opts, is_actor=True)
        options = scheduling_options(opts)
        process_runtime_env(client, opts, options)
        options["max_restarts"] = opts.get("max_restarts", 0)
        options["max_concurrency"] = opts.get("max_concurrency", 1)
        if opts.get("name"):
            options["name"] = opts["name"]
            options["namespace"] = opts.get("namespace")
        options["lifetime"] = opts.get("lifetime")
        actor_id, ready_id = client.create_actor(
            fn_id, args_kind, args_payload, deps, resources, options
        )
        ready_ref = ObjectRef(ready_id)
        # spilled creation args are hub-pinned for the actor's lifetime;
        # the twins on the ready ref let ownership GC reclaim them once
        # both the handle's ready ref is gone and the actor is dead
        ready_ref._hold = holds or None
        return ActorHandle(ActorID(actor_id.binary()), ready_ref)

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            f"use '{self.__name__}.remote()'."
        )
