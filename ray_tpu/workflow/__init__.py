"""Workflows: durable DAG execution with exactly-once node semantics.

Parity: python/ray/workflow/ (workflow_executor.py + workflow_storage.py)
— a DAG (the same `fn.bind` graphs ray_tpu.dag builds) runs with every
node's result checkpointed to storage as it completes; a crashed or
interrupted workflow resumes by replaying ONLY the nodes without a
durable result. Storage layout:

    <storage>/<workflow_id>/status.json
    <storage>/<workflow_id>/results/<node_key>.pkl

Node keys are content-derived (function name + arg structure position in
the topo order), so resume matches results to nodes deterministically.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ..dag.dag_node import DAGNode, FunctionNode, InputNode

_storage_base: Optional[str] = None


def init(storage: str) -> None:
    """Set the workflow storage root (reference: workflow.init)."""
    global _storage_base
    _storage_base = os.path.expanduser(storage)
    os.makedirs(_storage_base, exist_ok=True)


def _storage() -> str:
    if _storage_base is None:
        raise RuntimeError("call ray_tpu.workflow.init(storage_dir) first")
    return _storage_base


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _node_key(node: DAGNode, index: int) -> str:
    name = ""
    if isinstance(node, FunctionNode):
        name = getattr(node._remote_fn, "__name__", "fn")
    return f"{index:04d}_{name}"


def _set_status(workflow_id: str, status: str, **extra) -> None:
    path = os.path.join(_wf_dir(workflow_id), "status.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dict(extra, status=status), f)
    os.replace(tmp, path)


def get_status(workflow_id: str) -> str:
    try:
        with open(os.path.join(_wf_dir(workflow_id), "status.json")) as f:
            return json.load(f)["status"]
    except OSError:
        return "NOT_FOUND"


def list_all() -> List[Dict[str, str]]:
    out = []
    base = _storage()
    for wid in sorted(os.listdir(base)):
        if os.path.isdir(os.path.join(base, wid)):
            out.append({"workflow_id": wid, "status": get_status(wid)})
    return out


def run(dag: DAGNode, *, workflow_id: str, args: Any = None) -> Any:
    """Execute (or resume) the DAG durably and return the root's result.

    Every FunctionNode runs as a normal task; its result is fetched and
    pickled to storage before dependents run (the reference checkpoints
    through its storage backends the same way). Nodes with durable
    results are skipped on re-run — crash anywhere, call run() again
    with the same workflow_id, and only unfinished nodes execute."""
    import cloudpickle

    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    wdir = _wf_dir(workflow_id)
    results_dir = os.path.join(wdir, "results")
    os.makedirs(results_dir, exist_ok=True)
    _set_status(workflow_id, "RUNNING")

    schedule = dag._topo()
    results: Dict[int, Any] = {}
    try:
        for index, node in enumerate(schedule):
            if isinstance(node, InputNode):
                results[node._id] = args
                continue
            if not isinstance(node, FunctionNode):
                # passthrough nodes (input attributes, multi-output)
                results[node._id] = node._apply(results, (args,), {})
                continue
            key = _node_key(node, index)
            path = os.path.join(results_dir, key + ".pkl")
            if os.path.exists(path):
                with open(path, "rb") as f:
                    results[node._id] = cloudpickle.load(f)
                continue
            ref = node._apply(results, (args,), {})
            value = ray_tpu.get(ref)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                cloudpickle.dump(value, f)
            os.replace(tmp, path)  # durable BEFORE dependents may run
            results[node._id] = value
    except Exception:
        _set_status(workflow_id, "FAILED")
        raise
    _set_status(workflow_id, "SUCCEEDED")
    return results[dag._id]


def resume(workflow_id: str, dag: DAGNode, *, args: Any = None) -> Any:
    """Alias of run() — resumption IS re-running with the same id."""
    return run(dag, workflow_id=workflow_id, args=args)

from ray_tpu._private import usage as _usage

_usage.record_library_usage("workflow")
