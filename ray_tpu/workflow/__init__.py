"""Workflows: durable DAG execution with exactly-once node semantics.

Parity: python/ray/workflow/ (workflow_executor.py + workflow_storage.py)
— a DAG (the same `fn.bind` graphs ray_tpu.dag builds) runs with every
node's result checkpointed to storage as it completes; a crashed or
interrupted workflow resumes by replaying ONLY the nodes without a
durable result. Storage layout:

    <storage>/<workflow_id>/status.json
    <storage>/<workflow_id>/results/<node_key>.pkl

Node keys are content-derived (function name + arg structure position in
the topo order), so resume matches results to nodes deterministically.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ..dag.dag_node import DAGNode, FunctionNode, InputNode

_storage_base: Optional[str] = None


def init(storage: str) -> None:
    """Set the workflow storage root (reference: workflow.init)."""
    global _storage_base
    _storage_base = os.path.expanduser(storage)
    os.makedirs(_storage_base, exist_ok=True)


def _storage() -> str:
    if _storage_base is None:
        raise RuntimeError("call ray_tpu.workflow.init(storage_dir) first")
    return _storage_base


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _node_key(node: DAGNode, index: int) -> str:
    name = ""
    if isinstance(node, FunctionNode):
        name = getattr(node._remote_fn, "__name__", "fn")
    return f"{index:04d}_{name}"


def _set_status(workflow_id: str, status: str, **extra) -> None:
    path = os.path.join(_wf_dir(workflow_id), "status.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dict(extra, status=status), f)
    os.replace(tmp, path)


def get_status(workflow_id: str) -> str:
    try:
        with open(os.path.join(_wf_dir(workflow_id), "status.json")) as f:
            return json.load(f)["status"]
    except OSError:
        return "NOT_FOUND"


def list_all() -> List[Dict[str, str]]:
    out = []
    base = _storage()
    for wid in sorted(os.listdir(base)):
        if os.path.isdir(os.path.join(base, wid)):
            out.append({"workflow_id": wid, "status": get_status(wid)})
    return out


def run(dag: DAGNode, *, workflow_id: str, args: Any = None) -> Any:
    """Execute (or resume) the DAG durably and return the root's result.

    Every FunctionNode runs as a normal task; its result is fetched and
    pickled to storage before dependents run (the reference checkpoints
    through its storage backends the same way). Nodes with durable
    results are skipped on re-run — crash anywhere, call run() again
    with the same workflow_id, and only unfinished nodes execute."""
    import cloudpickle

    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    wdir = _wf_dir(workflow_id)
    results_dir = os.path.join(wdir, "results")
    os.makedirs(results_dir, exist_ok=True)
    _set_status(workflow_id, "RUNNING")

    # Concurrent executor (reference: workflow_executor.py runs every
    # in-flight node as a task and reacts to completions): all nodes
    # whose deps are durable submit IMMEDIATELY — independent branches
    # overlap; each result is persisted the moment it lands, before any
    # dependent can observe it.
    schedule = dag._topo()
    index_of = {node._id: i for i, node in enumerate(schedule)}
    deps: Dict[int, set] = {
        n._id: {c._id for c in n._children()} for n in schedule
    }
    dependents: Dict[int, List[DAGNode]] = {}
    for n in schedule:
        for c in n._children():
            dependents.setdefault(c._id, []).append(n)
    results: Dict[int, Any] = {}
    in_flight: Dict[Any, DAGNode] = {}  # ObjectRef -> node
    started: set = set()

    def _persist(node: DAGNode, value: Any) -> None:
        path = os.path.join(
            results_dir, _node_key(node, index_of[node._id]) + ".pkl"
        )
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, path)  # durable BEFORE dependents may run

    from collections import deque as _deque

    worklist: "_deque" = _deque()  # nodes whose deps are all in `results`

    def _start(node: DAGNode) -> None:
        """Deps are all in `results`; run or restore this node.
        Iterative (worklist, not recursion): restored/passthrough chains
        can be thousands of nodes deep."""
        if node._id in started:
            return
        started.add(node._id)
        if isinstance(node, InputNode):
            _finish(node, args)
            return
        if not isinstance(node, FunctionNode):
            # passthrough nodes (input attributes, multi-output)
            _finish(node, node._apply(results, (args,), {}))
            return
        path = os.path.join(
            results_dir, _node_key(node, index_of[node._id]) + ".pkl"
        )
        if os.path.exists(path):
            with open(path, "rb") as f:
                _finish(node, cloudpickle.load(f))
            return
        in_flight[node._apply(results, (args,), {})] = node

    def _finish(node: DAGNode, value: Any) -> None:
        results[node._id] = value
        for dep in dependents.get(node._id, ()):
            deps[dep._id].discard(node._id)
            if not deps[dep._id]:
                worklist.append(dep)

    def _drain() -> None:
        while worklist:
            _start(worklist.popleft())

    try:
        for node in schedule:
            if not deps[node._id]:
                worklist.append(node)
        _drain()
        while in_flight:
            done, _ = ray_tpu.wait(list(in_flight), num_returns=1)
            node = in_flight.pop(done[0])
            value = ray_tpu.get(done[0])
            _persist(node, value)
            _finish(node, value)
            _drain()
    except Exception:
        _set_status(workflow_id, "FAILED")
        raise
    _set_status(workflow_id, "SUCCEEDED")
    return results[dag._id]


def resume(workflow_id: str, dag: DAGNode, *, args: Any = None) -> Any:
    """Alias of run() — resumption IS re-running with the same id."""
    return run(dag, workflow_id=workflow_id, args=args)

from ray_tpu._private import usage as _usage

_usage.record_library_usage("workflow")
