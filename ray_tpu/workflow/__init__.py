"""Workflows: durable DAG execution with exactly-once node semantics.

Parity: python/ray/workflow/ (workflow_executor.py + workflow_storage.py)
— a DAG (the same `fn.bind` graphs ray_tpu.dag builds) runs with every
node's result checkpointed to storage as it completes; a crashed or
interrupted workflow resumes by replaying ONLY the nodes without a
durable result. Storage layout:

    <storage>/<workflow_id>/status.json
    <storage>/<workflow_id>/results/<node_key>.pkl

Node keys are content-derived (function name + arg structure position in
the topo order), so resume matches results to nodes deterministically.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ..dag.dag_node import DAGNode, FunctionNode, InputNode

_storage_base: Optional[str] = None


class EventListener:
    """External-event source for durable workflows (reference:
    python/ray/workflow/event_listener.py EventListener ABC).

    `poll_for_event()` blocks until the event occurs and returns its
    payload; `event_checkpointed(event)` runs AFTER the payload is
    durably persisted — the commit hook where e.g. a queue message is
    acked, giving exactly-once delivery INTO the workflow (the payload
    checkpoint is consulted before any re-poll on resume)."""

    def poll_for_event(self) -> Any:
        raise NotImplementedError

    def event_checkpointed(self, event: Any) -> None:
        pass


class EventNode(DAGNode):
    """A durable wait-for-event step (reference: workflow.wait_for_event
    building a WaitForEvent step). Resume semantics: a checkpointed
    payload short-circuits the poll entirely."""

    def __init__(self, listener_cls, args, kwargs):
        super().__init__()
        if not (isinstance(listener_cls, type)
                and issubclass(listener_cls, EventListener)):
            raise TypeError(
                "wait_for_event expects an EventListener subclass"
            )
        self._listener_cls = listener_cls
        self._listener_args = args
        self._listener_kwargs = kwargs

    def make_listener(self) -> EventListener:
        return self._listener_cls(
            *self._listener_args, **self._listener_kwargs
        )

    def _apply(self, results, input_args, input_kwargs):
        import cloudpickle

        import ray_tpu

        blob = cloudpickle.dumps(
            (self._listener_cls, self._listener_args, self._listener_kwargs)
        )

        @ray_tpu.remote
        def _poll_for_event(b):
            import cloudpickle as _cp

            cls, a, kw = _cp.loads(b)
            return cls(*a, **kw).poll_for_event()

        return _poll_for_event.remote(blob)


def wait_for_event(listener_cls, *args, **kwargs) -> EventNode:
    """Bind an external-event wait into a workflow DAG (reference:
    workflow/api.py wait_for_event)."""
    return EventNode(listener_cls, args, kwargs)


def init(storage: str) -> None:
    """Set the workflow storage root (reference: workflow.init)."""
    global _storage_base
    _storage_base = os.path.expanduser(storage)
    os.makedirs(_storage_base, exist_ok=True)


def _storage() -> str:
    if _storage_base is None:
        raise RuntimeError("call ray_tpu.workflow.init(storage_dir) first")
    return _storage_base


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _node_key(node: DAGNode, index: int) -> str:
    name = ""
    if isinstance(node, FunctionNode):
        name = getattr(node._remote_fn, "__name__", "fn")
    elif isinstance(node, EventNode):
        name = f"event_{node._listener_cls.__name__}"
    return f"{index:04d}_{name}"


def _set_status(workflow_id: str, status: str, **extra) -> None:
    path = os.path.join(_wf_dir(workflow_id), "status.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dict(extra, status=status), f)
    os.replace(tmp, path)


def get_status(workflow_id: str) -> str:
    try:
        with open(os.path.join(_wf_dir(workflow_id), "status.json")) as f:
            return json.load(f)["status"]
    except OSError:
        return "NOT_FOUND"


def list_all() -> List[Dict[str, str]]:
    out = []
    base = _storage()
    for wid in sorted(os.listdir(base)):
        if os.path.isdir(os.path.join(base, wid)):
            out.append({"workflow_id": wid, "status": get_status(wid)})
    return out


def run(dag: DAGNode, *, workflow_id: str, args: Any = None) -> Any:
    """Execute (or resume) the DAG durably and return the root's result.

    Every FunctionNode runs as a normal task; its result is fetched and
    pickled to storage before dependents run (the reference checkpoints
    through its storage backends the same way). Nodes with durable
    results are skipped on re-run — crash anywhere, call run() again
    with the same workflow_id, and only unfinished nodes execute."""
    import cloudpickle

    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    wdir = _wf_dir(workflow_id)
    results_dir = os.path.join(wdir, "results")
    os.makedirs(results_dir, exist_ok=True)
    _set_status(workflow_id, "RUNNING")

    # Concurrent executor (reference: workflow_executor.py runs every
    # in-flight node as a task and reacts to completions): all nodes
    # whose deps are durable submit IMMEDIATELY — independent branches
    # overlap; each result is persisted the moment it lands, before any
    # dependent can observe it.
    schedule = dag._topo()
    index_of = {node._id: i for i, node in enumerate(schedule)}
    deps: Dict[int, set] = {
        n._id: {c._id for c in n._children()} for n in schedule
    }
    dependents: Dict[int, List[DAGNode]] = {}
    for n in schedule:
        for c in n._children():
            dependents.setdefault(c._id, []).append(n)
    results: Dict[int, Any] = {}
    in_flight: Dict[Any, DAGNode] = {}  # ObjectRef -> node
    started: set = set()

    def _persist(node: DAGNode, value: Any) -> None:
        path = os.path.join(
            results_dir, _node_key(node, index_of[node._id]) + ".pkl"
        )
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, path)  # durable BEFORE dependents may run

    def _ack_event(node: "EventNode", path: str, value: Any) -> None:
        """Commit hook AFTER the durable checkpoint (reference:
        event_listener.event_checkpointed — ack the source). The
        .acked marker makes the hook itself resumable: a crash
        between persist and ack re-runs ONLY the hook."""
        node.make_listener().event_checkpointed(value)
        with open(path + ".acked", "w") as f:
            f.write("1")

    from collections import deque as _deque

    worklist: "_deque" = _deque()  # nodes whose deps are all in `results`

    def _start(node: DAGNode) -> None:
        """Deps are all in `results`; run or restore this node.
        Iterative (worklist, not recursion): restored/passthrough chains
        can be thousands of nodes deep."""
        if node._id in started:
            return
        started.add(node._id)
        if isinstance(node, InputNode):
            _finish(node, args)
            return
        if not isinstance(node, (FunctionNode, EventNode)):
            # passthrough nodes (input attributes, multi-output)
            _finish(node, node._apply(results, (args,), {}))
            return
        path = os.path.join(
            results_dir, _node_key(node, index_of[node._id]) + ".pkl"
        )
        if os.path.exists(path):
            # exactly-once: a checkpointed event payload (or task
            # result) is NEVER re-polled/re-run on resume
            with open(path, "rb") as f:
                value = cloudpickle.load(f)
            if isinstance(node, EventNode) and not os.path.exists(
                path + ".acked"
            ):
                # crashed between persist and the commit hook: re-run
                # the hook (at-least-once ack, exactly-once payload)
                _ack_event(node, path, value)
            _finish(node, value)
            return
        in_flight[node._apply(results, (args,), {})] = node

    def _finish(node: DAGNode, value: Any) -> None:
        results[node._id] = value
        for dep in dependents.get(node._id, ()):
            deps[dep._id].discard(node._id)
            if not deps[dep._id]:
                worklist.append(dep)

    def _drain() -> None:
        while worklist:
            _start(worklist.popleft())

    try:
        for node in schedule:
            if not deps[node._id]:
                worklist.append(node)
        _drain()
        while in_flight:
            done, _ = ray_tpu.wait(list(in_flight), num_returns=1)
            node = in_flight.pop(done[0])
            value = ray_tpu.get(done[0])
            _persist(node, value)
            if isinstance(node, EventNode):
                _ack_event(
                    node,
                    os.path.join(
                        results_dir,
                        _node_key(node, index_of[node._id]) + ".pkl",
                    ),
                    value,
                )
            _finish(node, value)
            _drain()
    except Exception:
        _set_status(workflow_id, "FAILED")
        raise
    _set_status(workflow_id, "SUCCEEDED")
    return results[dag._id]


def resume(workflow_id: str, dag: DAGNode, *, args: Any = None) -> Any:
    """Alias of run() — resumption IS re-running with the same id."""
    return run(dag, workflow_id=workflow_id, args=args)

from ray_tpu._private import usage as _usage

_usage.record_library_usage("workflow")
