"""Replica actor: hosts one copy of a deployment's user callable.

Parity: python/ray/serve/_private/replica.py — wraps the user class,
counts ongoing requests (the router's load signal), health checks,
graceful reconfigure.
"""

from __future__ import annotations

import asyncio
import inspect
import threading
import time
from typing import Any, Dict, Optional, Tuple

# serve-scope chaos engine (slow_replica execute-latency injection),
# built once per replica process; None-cached when the plan is inert
_chaos_engine = None
_chaos_ready = False


def _chaos():
    global _chaos_engine, _chaos_ready
    if not _chaos_ready:
        from ..._private import chaos as chaos_mod

        _chaos_engine = chaos_mod.engine_for("serve")
        _chaos_ready = True
    return _chaos_engine


class Replica:
    def __init__(
        self,
        deployment_name: str,
        serialized_cls,  # the user class (cloudpickled through task args)
        init_args: Tuple,
        init_kwargs: Dict[str, Any],
        user_config: Any = None,
    ):
        self.deployment_name = deployment_name
        self._ongoing = 0
        self._lock = threading.Lock()
        self._total = 0
        from . import observability as obs

        # lets @serve.batch queues and multiplex wrappers (which never
        # see the Replica) tag their metrics with this deployment
        obs.set_current_deployment(deployment_name)
        # profiler attribution: this worker process's samples read
        # worker:serve:<deployment> instead of bare "worker"
        from ..._private import profiling as _profiling

        _profiling.set_process_label(f"serve:{deployment_name}")
        cls = serialized_cls
        if callable(cls) and not inspect.isclass(cls):
            # function deployment: wrap into a callable object
            fn = cls

            class _FnWrapper:
                def __call__(self, *a, **k):
                    return fn(*a, **k)

            self.instance = _FnWrapper()
        else:
            self.instance = cls(*init_args, **init_kwargs)
        if user_config is not None and hasattr(self.instance, "reconfigure"):
            self.instance.reconfigure(user_config)

    # -- introspection (router load probes, controller health checks) --
    def queue_len(self) -> int:
        return self._ongoing

    def stats(self) -> Dict[str, Any]:
        from ..batching import queued_total
        from ..multiplex import registered_model_ids

        return {
            "ongoing": self._ongoing,
            "total": self._total,
            "queued": queued_total(),
            "multiplexed_model_ids": registered_model_ids(),
        }

    def check_health(self) -> bool:
        fn = getattr(self.instance, "check_health", None)
        if fn is not None:
            fn()
        return True

    def reconfigure(self, user_config: Any) -> None:
        if hasattr(self.instance, "reconfigure"):
            self.instance.reconfigure(user_config)

    # -- request path --------------------------------------------------
    def handle_request(
        self,
        method_name: str,
        args: Tuple,
        kwargs: Dict,
        multiplexed_model_id: str = "",
        request_meta: Optional[Dict[str, Any]] = None,
    ):
        from ...util import tracing as _tracing
        from ..multiplex import _model_id_ctx
        from . import observability as obs
        from . import payloads as _payloads

        with self._lock:
            self._ongoing += 1
            self._total += 1
        # deadline propagation: the router stamped deadline_wall into
        # request_meta; convert to THIS process's monotonic clock (same
        # host, anchored wall offset). An already-expired request is
        # dropped HERE — before payload resolution and before the user
        # callable burns replica time.
        deadline_mono: Optional[float] = None
        if request_meta and "deadline_wall" in request_meta:
            t_now = time.monotonic()
            deadline_mono = t_now + (
                request_meta["deadline_wall"] - _tracing.wall_at(t_now)
            )
            if deadline_mono <= t_now:
                with self._lock:
                    self._ongoing -= 1
                obs.count_expired(self.deployment_name)
                from ray_tpu.exceptions import RequestExpiredError

                raise RequestExpiredError(self.deployment_name)
        # slow_replica chaos: injected execute latency, drawn from the
        # serve-scope rng in request-arrival order
        eng = _chaos()
        if eng is not None:
            d = eng.execute_delay(self.deployment_name)
            if d > 0.0:
                time.sleep(d)
        # traced request: the worker's _ExecTrace pushed (trace_id,
        # execute-span-id) as the ambient context before dispatching this
        # actor method. serve.queue_wait back-fills the handle-enqueue ->
        # here gap (start reconstructed from the enq_wall stamp the
        # router sent along); serve.execute wraps the user callable.
        ctx = _tracing.current_context()
        exec_sid = None
        if ctx is not None:
            t_in = time.monotonic()
            if request_meta and "enq_wall" in request_meta:
                obs.emit_span(
                    "serve.queue_wait", "serve.queue_wait", ctx[0], ctx[1],
                    obs.mono_at_wall(request_meta["enq_wall"], t_in), t_in,
                    deployment=self.deployment_name,
                )
            exec_sid = _tracing.new_span_id()
        from ..batching import _deadline_ctx

        token = _model_id_ctx.set(multiplexed_model_id)
        dl_token = _deadline_ctx.set(deadline_mono)
        trace_token = (
            _tracing.push_context((ctx[0], exec_sid)) if exec_sid else None
        )
        t0 = time.monotonic()
        try:
            target = (
                self.instance
                if method_name == "__call__"
                else getattr(self.instance, method_name)
            )
            # zero-copy payload plane: bulk-resolve PayloadRef markers
            # (and top-level ObjectRefs — composition args) in ONE get
            # before the user callable runs; raw bodies arrive as
            # memoryviews over the mapped segment. @serve.batch targets
            # defer to the batch queue so the whole batch shares one
            # fetch (batching._BatchQueue._loop).
            if not _payloads.is_batch_target(target):
                t_fetch0 = time.monotonic()
                args, kwargs, n_fetched, fetched_bytes = (
                    _payloads.resolve_args(args, kwargs)
                )
                if n_fetched and ctx is not None:
                    obs.emit_span(
                        "serve.payload_fetch", "serve.payload_fetch",
                        ctx[0], ctx[1], t_fetch0, time.monotonic(),
                        deployment=self.deployment_name,
                        n=n_fetched, nbytes=fetched_bytes,
                    )
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                # the coroutine executes on the replica loop THREAD —
                # re-enter the model-id (and trace) context there, the
                # caller thread's contextvars don't cross
                async def _with_ctx(coro=result):
                    tok = _model_id_ctx.set(multiplexed_model_id)
                    # the deadline rides to the loop thread too, so a
                    # @serve.batch submit parks it alongside the member
                    dtok = _deadline_ctx.set(deadline_mono)
                    ttok = (
                        _tracing.push_context((ctx[0], exec_sid))
                        if exec_sid
                        else None
                    )
                    try:
                        return await coro
                    finally:
                        if ttok is not None:
                            _tracing.pop_context(ttok)
                        _deadline_ctx.reset(dtok)
                        _model_id_ctx.reset(tok)

                result = _run_coro(_with_ctx())
            # oversized raw results ride back as shm segments instead
            # of pickling through the hub (payloads.wrap_result)
            return _payloads.wrap_result(result)
        finally:
            if trace_token is not None:
                _tracing.pop_context(trace_token)
            if exec_sid is not None:
                obs.emit_span(
                    "serve.execute", "serve.execute", ctx[0], ctx[1],
                    t0, time.monotonic(), span_id=exec_sid,
                    deployment=self.deployment_name, method=method_name,
                )
            _deadline_ctx.reset(dl_token)
            _model_id_ctx.reset(token)
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(
        self,
        method_name: str,
        args: Tuple,
        kwargs: Dict,
        multiplexed_model_id: str = "",
    ):
        """Generator variant: invoked with num_returns="streaming" so
        each yielded chunk becomes an incremental stream object
        (reference: Serve streaming responses over ObjectRefGenerator)."""
        from ..multiplex import _model_id_ctx

        with self._lock:
            self._ongoing += 1
            self._total += 1
        # no reset token: the executor drives one task at a time, and
        # generator frames don't carry their own context anyway
        _model_id_ctx.set(multiplexed_model_id)
        try:
            target = (
                self.instance
                if method_name == "__call__"
                else getattr(self.instance, method_name)
            )
            result = target(*args, **kwargs)

            async def _with_ctx(coro):
                # async steps execute on the replica loop THREAD; re-enter
                # the model-id context there (mirror of handle_request)
                tok = _model_id_ctx.set(multiplexed_model_id)
                try:
                    return await coro
                finally:
                    _model_id_ctx.reset(tok)

            if inspect.isgenerator(result):
                yield from result
            elif inspect.isasyncgen(result):
                # drain the async generator on the replica's loop
                while True:
                    try:
                        yield _run_coro(_with_ctx(result.__anext__()))
                    except StopAsyncIteration:
                        break
            else:
                if inspect.iscoroutine(result):
                    result = _run_coro(_with_ctx(result))
                yield result
        finally:
            with self._lock:
                self._ongoing -= 1


_loop: Optional[asyncio.AbstractEventLoop] = None
_loop_lock = threading.Lock()


def _run_coro(coro):
    """Run a coroutine from sync context on a persistent loop (user
    callables may be async — e.g. @serve.batch methods)."""
    global _loop
    with _loop_lock:
        if _loop is None:
            _loop = asyncio.new_event_loop()
            t = threading.Thread(target=_loop.run_forever, daemon=True, name="replica-aio")
            t.start()
    fut = asyncio.run_coroutine_threadsafe(coro, _loop)
    return fut.result()
