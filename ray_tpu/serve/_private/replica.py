"""Replica actor: hosts one copy of a deployment's user callable.

Parity: python/ray/serve/_private/replica.py — wraps the user class,
counts ongoing requests (the router's load signal), health checks,
graceful reconfigure.
"""

from __future__ import annotations

import asyncio
import inspect
import threading
import time
from typing import Any, Dict, Optional, Tuple


class Replica:
    def __init__(
        self,
        deployment_name: str,
        serialized_cls,  # the user class (cloudpickled through task args)
        init_args: Tuple,
        init_kwargs: Dict[str, Any],
        user_config: Any = None,
    ):
        self.deployment_name = deployment_name
        self._ongoing = 0
        self._lock = threading.Lock()
        self._total = 0
        cls = serialized_cls
        if callable(cls) and not inspect.isclass(cls):
            # function deployment: wrap into a callable object
            fn = cls

            class _FnWrapper:
                def __call__(self, *a, **k):
                    return fn(*a, **k)

            self.instance = _FnWrapper()
        else:
            self.instance = cls(*init_args, **init_kwargs)
        if user_config is not None and hasattr(self.instance, "reconfigure"):
            self.instance.reconfigure(user_config)

    # -- introspection (router load probes, controller health checks) --
    def queue_len(self) -> int:
        return self._ongoing

    def stats(self) -> Dict[str, Any]:
        from ..multiplex import registered_model_ids

        return {
            "ongoing": self._ongoing,
            "total": self._total,
            "multiplexed_model_ids": registered_model_ids(),
        }

    def check_health(self) -> bool:
        fn = getattr(self.instance, "check_health", None)
        if fn is not None:
            fn()
        return True

    def reconfigure(self, user_config: Any) -> None:
        if hasattr(self.instance, "reconfigure"):
            self.instance.reconfigure(user_config)

    # -- request path --------------------------------------------------
    def handle_request(
        self,
        method_name: str,
        args: Tuple,
        kwargs: Dict,
        multiplexed_model_id: str = "",
    ):
        from ..multiplex import _model_id_ctx

        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = _model_id_ctx.set(multiplexed_model_id)
        try:
            target = (
                self.instance
                if method_name == "__call__"
                else getattr(self.instance, method_name)
            )
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                # the coroutine executes on the replica loop THREAD —
                # re-enter the model-id context there, the caller
                # thread's contextvar doesn't cross
                async def _with_ctx(coro=result):
                    tok = _model_id_ctx.set(multiplexed_model_id)
                    try:
                        return await coro
                    finally:
                        _model_id_ctx.reset(tok)

                result = _run_coro(_with_ctx())
            return result
        finally:
            _model_id_ctx.reset(token)
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(
        self,
        method_name: str,
        args: Tuple,
        kwargs: Dict,
        multiplexed_model_id: str = "",
    ):
        """Generator variant: invoked with num_returns="streaming" so
        each yielded chunk becomes an incremental stream object
        (reference: Serve streaming responses over ObjectRefGenerator)."""
        from ..multiplex import _model_id_ctx

        with self._lock:
            self._ongoing += 1
            self._total += 1
        # no reset token: the executor drives one task at a time, and
        # generator frames don't carry their own context anyway
        _model_id_ctx.set(multiplexed_model_id)
        try:
            target = (
                self.instance
                if method_name == "__call__"
                else getattr(self.instance, method_name)
            )
            result = target(*args, **kwargs)

            async def _with_ctx(coro):
                # async steps execute on the replica loop THREAD; re-enter
                # the model-id context there (mirror of handle_request)
                tok = _model_id_ctx.set(multiplexed_model_id)
                try:
                    return await coro
                finally:
                    _model_id_ctx.reset(tok)

            if inspect.isgenerator(result):
                yield from result
            elif inspect.isasyncgen(result):
                # drain the async generator on the replica's loop
                while True:
                    try:
                        yield _run_coro(_with_ctx(result.__anext__()))
                    except StopAsyncIteration:
                        break
            else:
                if inspect.iscoroutine(result):
                    result = _run_coro(_with_ctx(result))
                yield result
        finally:
            with self._lock:
                self._ongoing -= 1


_loop: Optional[asyncio.AbstractEventLoop] = None
_loop_lock = threading.Lock()


def _run_coro(coro):
    """Run a coroutine from sync context on a persistent loop (user
    callables may be async — e.g. @serve.batch methods)."""
    global _loop
    with _loop_lock:
        if _loop is None:
            _loop = asyncio.new_event_loop()
            t = threading.Thread(target=_loop.run_forever, daemon=True, name="replica-aio")
            t.start()
    fut = asyncio.run_coroutine_threadsafe(coro, _loop)
    return fut.result()
