"""HTTP proxy actor: aiohttp ingress routing to deployments.

Parity: python/ray/serve/_private/proxy.py (uvicorn there; aiohttp
here — it's what the environment ships, and it's the reference's own
dashboard HTTP stack) + proxy_router.py longest-prefix route matching.
The request reaches the app as a dict {method, path, query, body,
headers}; the deployment's return value is JSON-encoded (bytes/str pass
through).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional


def _error_status(e: BaseException) -> int:
    """HTTP status bucket for an ingress failure. Replica-raised
    exceptions arrive wrapped in TaskError — classify on the cause.
    503 = shed (admission control, retriable), 504 = deadline expired
    (router wait, pre-execute drop, or result() deadline), 500 = rest.
    """
    from ray_tpu.exceptions import (
        GetTimeoutError,
        RequestExpiredError,
        RequestShedError,
        TaskError,
    )

    cause = e
    if isinstance(e, TaskError) and e.cause is not None:
        cause = e.cause
    if isinstance(cause, RequestShedError):
        return 503
    if isinstance(cause, (RequestExpiredError, GetTimeoutError)):
        return 504
    return 500


def _request_timeout_override(raw: Optional[str]) -> Optional[float]:
    """Parse a per-request deadline override (header/metadata value)."""
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._routes: Dict[str, str] = {}
        self._routes_refreshed = float("-inf")
        self._handles: Dict[str, Any] = {}
        self._loop = asyncio.new_event_loop()
        self._ready = threading.Event()
        self._thread = threading.Thread(
            target=self._serve_forever, daemon=True, name="serve-http"
        )
        self._thread.start()
        self._ready.wait(timeout=10)  # graftlint: disable=GL017 — pre-request startup gate; no request (hence no deadline) exists yet

    def _serve_forever(self):
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._start())
        self._loop.run_forever()

    async def _start(self):
        from aiohttp import web

        # aiohttp's default client_max_size (1 MiB) would 413 exactly
        # the bodies the zero-copy payload plane exists for; cap at the
        # configurable ingress limit instead (default 1 GiB)
        from ..._private import config as _config

        max_body = int(
            _config.RAY_TPU_CONFIG.get("serve_http_max_body", 1 << 30)
        )
        app = web.Application(client_max_size=max_body)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._ready.set()

    def update_routes(self, routes: Dict[str, str]) -> None:
        self._routes = dict(routes)

    def ping(self) -> bool:
        return self._ready.is_set()

    def _match(self, path: str) -> Optional[tuple]:
        """Longest-prefix route match -> (route_prefix, deployment name)."""
        best = None
        for prefix, name in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, name)
        return best

    async def _handle(self, request):
        from aiohttp import web

        import time as _time

        from ...util import tracing as _tracing
        from . import observability as obs

        t_in = _time.monotonic()
        # periodic cached refresh, off the event loop (a controller
        # stall must not freeze unrelated in-flight requests)
        if _time.monotonic() - self._routes_refreshed > 1.0:
            self._routes_refreshed = _time.monotonic()
            await asyncio.get_running_loop().run_in_executor(
                None, self._refresh_routes
            )
        matched = self._match(request.path)
        if matched is None:
            return web.Response(status=404, text="no deployment matches path")
        route_prefix, name = matched
        handle = self._handles.get(name)
        if handle is None:
            from ..handle import DeploymentHandle

            handle = DeploymentHandle(name)
            self._handles[name] = handle
        handle._metric_route = route_prefix
        # per-request deadline override; otherwise the handle derives
        # the deadline from serve_request_timeout_s and every blocking
        # wait below (route + result) is capped by it — no literal 60 s
        t_override = _request_timeout_override(
            request.headers.get("X-Request-Timeout-S")
        )
        if t_override is not None:
            handle = handle.options(request_timeout_s=t_override)
        body = await request.read()
        req = {
            "method": request.method,
            "path": request.path,
            "query": dict(request.query),
            "body": body,
            "headers": dict(request.headers),
        }
        # head-sample here — the ingress is the trace root for a serve
        # request. serve.proxy_recv covers recv + parse + route match.
        tr = obs.begin_trace()
        proxy_sid = None
        if tr is not None:
            proxy_sid = obs.emit_span(
                "serve.proxy_recv", "serve.proxy_recv", tr[0], tr[1],
                t_in, _time.monotonic(),
                http_method=request.method, path=request.path,
                deployment=name,
            )
        try:
            # routing involves blocking control-plane calls; keep the
            # event loop free by doing route+wait on a worker thread.
            # run_in_executor does NOT carry contextvars: re-push the
            # trace context inside the worker-thread closure so the
            # router inherits it.
            if proxy_sid is None:
                result = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: handle.remote(req).result()
                )
            else:

                def _routed():
                    token = _tracing.push_context((tr[0], proxy_sid))
                    try:
                        return handle.remote(req).result()
                    finally:
                        _tracing.pop_context(token)

                result = await asyncio.get_running_loop().run_in_executor(
                    None, _routed
                )
        except Exception as e:
            status = _error_status(e)
            headers = {"Retry-After": "1"} if status == 503 else None
            return web.Response(
                status=status,
                text=f"{type(e).__name__}: {e}",
                headers=headers,
            )
        t_resp0 = _time.monotonic()
        resp = self._encode(result)
        if proxy_sid is not None:
            obs.emit_span(
                "serve.response_return", "serve.response_return",
                tr[0], proxy_sid, t_resp0, _time.monotonic(),
                status=resp.status, deployment=name,
            )
        return resp

    def _encode(self, result):
        """Deployment return value -> aiohttp Response."""
        from aiohttp import web

        from ..response import Response as ServeResponse

        if isinstance(result, ServeResponse):
            # explicit status/content-type/headers from the deployment
            # (reference: returning a starlette Response). aiohttp
            # forbids (a) a Content-Type header alongside the
            # content_type param and (b) a charset inside content_type
            # — normalize both starlette-style spellings.
            headers = {
                k: v for k, v in result.headers.items()
                if k.lower() != "content-type"
            }
            ctype = next(
                (v for k, v in result.headers.items()
                 if k.lower() == "content-type"),
                result.content_type,
            )
            charset = None
            if ";" in ctype:
                ctype, _, rest = ctype.partition(";")
                ctype = ctype.strip()
                rest = rest.strip()
                if rest.lower().startswith("charset="):
                    charset = rest.split("=", 1)[1]
            return web.Response(
                status=result.status,
                body=result.body_bytes(),
                content_type=ctype,
                charset=charset,
                headers=headers,
            )
        if isinstance(result, (bytes, bytearray, memoryview)):
            # memoryview: a zero-copy payload-plane body straight off the
            # mapped response segment — aiohttp's BytesPayload writes
            # bytes-like objects as-is, so no copy here either
            return web.Response(
                body=result, content_type="application/octet-stream"
            )
        if isinstance(result, str):
            return web.Response(text=result)
        return web.json_response(result)

    def _refresh_routes(self) -> None:
        try:
            import ray_tpu

            from .controller import CONTROLLER_NAME

            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            self._routes = ray_tpu.get(controller.get_routes.remote())
        except Exception:
            pass


class GrpcIngress:
    """gRPC ingress (reference: serve/_private/proxy.py gRPCProxy +
    grpc_util.py). A generic unary-unary service — no protoc step:
    requests route by the `route` metadata key (falling back to the
    first segment of the method path, mirroring the reference's
    `application` metadata routing), the deployment receives
    {"grpc_method", "body", "metadata"} and returns bytes/str/JSON-able,
    serialized back as raw response bytes."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        import time as _time
        from concurrent import futures

        import grpc

        self.host = host
        self._routes: Dict[str, str] = {}
        self._routes_refreshed = float("-inf")
        self._handles: Dict[str, Any] = {}
        ingress = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, details):
                method = details.method

                def call(request: bytes, context):
                    return ingress._call(method, request, context)

                return grpc.unary_unary_rpc_method_handler(
                    call,
                    request_deserializer=None,   # raw bytes in
                    response_serializer=None,    # raw bytes out
                )

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16)
        )
        self._server.add_generic_rpc_handlers((_Generic(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        self._time = _time

    def ping(self) -> int:
        return self.port

    def _call(self, method: str, request: bytes, context) -> bytes:
        import grpc

        from ...util import tracing as _tracing
        from . import observability as obs

        t_in = self._time.monotonic()
        if self._time.monotonic() - self._routes_refreshed > 1.0:
            self._routes_refreshed = self._time.monotonic()
            self._refresh_routes()
        md = {k: v for k, v in (context.invocation_metadata() or ())}
        route = md.get("route")
        if route is None:
            # "/pkg.Service/Method" -> "/pkg.Service"
            route = "/" + method.strip("/").split("/")[0]
        matched = self._match(route if route.startswith("/") else f"/{route}")
        if matched is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"no deployment matches route {route!r}",
            )
        route_prefix, name = matched
        handle = self._handles.get(name)
        if handle is None:
            from ..handle import DeploymentHandle

            handle = DeploymentHandle(name)
            self._handles[name] = handle
        handle._metric_route = route_prefix
        # per-request deadline override via metadata (the gRPC twin of
        # the X-Request-Timeout-S header)
        t_override = _request_timeout_override(md.get("request-timeout-s"))
        if t_override is not None:
            handle = handle.options(request_timeout_s=t_override)
        req = {"grpc_method": method, "body": request, "metadata": md}
        tr = obs.begin_trace()
        proxy_sid = None
        if tr is not None:
            proxy_sid = obs.emit_span(
                "serve.proxy_recv", "serve.proxy_recv", tr[0], tr[1],
                t_in, self._time.monotonic(),
                grpc_method=method, deployment=name,
            )
        token = (
            _tracing.push_context((tr[0], proxy_sid))
            if proxy_sid is not None
            else None
        )
        try:
            result = handle.remote(req).result()
        except Exception as e:  # noqa: BLE001
            status = _error_status(e)
            code = (
                grpc.StatusCode.RESOURCE_EXHAUSTED
                if status == 503
                else grpc.StatusCode.DEADLINE_EXCEEDED
                if status == 504
                else grpc.StatusCode.INTERNAL
            )
            context.abort(code, f"{type(e).__name__}: {e}")
        finally:
            if token is not None:
                _tracing.pop_context(token)
        t_resp0 = self._time.monotonic()
        try:
            return self._encode_grpc(result, context)
        finally:
            if proxy_sid is not None:
                obs.emit_span(
                    "serve.response_return", "serve.response_return",
                    tr[0], proxy_sid, t_resp0, self._time.monotonic(),
                    deployment=name,
                )

    def _encode_grpc(self, result, context) -> bytes:
        import grpc
        from ..response import Response as ServeResponse

        if isinstance(result, ServeResponse):
            # shared deployments may return serve.Response on either
            # ingress: gRPC carries the body; an error status maps to
            # an INTERNAL abort (no HTTP status channel here)
            if result.status >= 400:
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"deployment returned status {result.status}",
                )
            return result.body_bytes()
        if isinstance(result, (bytes, bytearray, memoryview)):
            # memoryview: payload-plane body; grpc wants real bytes
            return bytes(result)
        if isinstance(result, str):
            return result.encode()
        return json.dumps(result).encode()

    _match = HTTPProxy._match
    _refresh_routes = HTTPProxy._refresh_routes

    def stop(self) -> None:
        self._server.stop(grace=0.5)
