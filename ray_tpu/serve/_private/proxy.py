"""HTTP proxy actor: aiohttp ingress routing to deployments.

Parity: python/ray/serve/_private/proxy.py (uvicorn there; aiohttp
here — it's what the environment ships, and it's the reference's own
dashboard HTTP stack) + proxy_router.py longest-prefix route matching.
The request reaches the app as a dict {method, path, query, body,
headers}; the deployment's return value is JSON-encoded (bytes/str pass
through).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._routes: Dict[str, str] = {}
        self._routes_refreshed = float("-inf")
        self._handles: Dict[str, Any] = {}
        self._loop = asyncio.new_event_loop()
        self._ready = threading.Event()
        self._thread = threading.Thread(
            target=self._serve_forever, daemon=True, name="serve-http"
        )
        self._thread.start()
        self._ready.wait(timeout=10)

    def _serve_forever(self):
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._start())
        self._loop.run_forever()

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._ready.set()

    def update_routes(self, routes: Dict[str, str]) -> None:
        self._routes = dict(routes)

    def ping(self) -> bool:
        return self._ready.is_set()

    def _match(self, path: str) -> Optional[str]:
        best = None
        for prefix, name in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, name)
        return best[1] if best else None

    async def _handle(self, request):
        from aiohttp import web

        import time as _time

        # periodic cached refresh, off the event loop (a controller
        # stall must not freeze unrelated in-flight requests)
        if _time.monotonic() - self._routes_refreshed > 1.0:
            self._routes_refreshed = _time.monotonic()
            await asyncio.get_running_loop().run_in_executor(
                None, self._refresh_routes
            )
        name = self._match(request.path)
        if name is None:
            return web.Response(status=404, text="no deployment matches path")
        handle = self._handles.get(name)
        if handle is None:
            from ..handle import DeploymentHandle

            handle = DeploymentHandle(name)
            self._handles[name] = handle
        body = await request.read()
        req = {
            "method": request.method,
            "path": request.path,
            "query": dict(request.query),
            "body": body,
            "headers": dict(request.headers),
        }
        try:
            # routing involves blocking control-plane calls; keep the
            # event loop free by doing route+wait on a worker thread
            result = await asyncio.get_running_loop().run_in_executor(
                None, lambda: handle.remote(req).result(timeout_s=60)
            )
        except Exception as e:
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        if isinstance(result, (bytes, bytearray)):
            return web.Response(body=bytes(result))
        if isinstance(result, str):
            return web.Response(text=result)
        return web.json_response(result)

    def _refresh_routes(self) -> None:
        try:
            import ray_tpu

            from .controller import CONTROLLER_NAME

            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            self._routes = ray_tpu.get(controller.get_routes.remote())
        except Exception:
            pass
