"""ServeController: the reconcile loop.

Parity: python/ray/serve/_private/controller.py:86 + deployment_state.py
— a singleton named actor holding target state {deployment -> config},
reconciling replica actors toward it, running autoscaling, and serving
discovery (the reference broadcasts routing tables via LongPollHost; on
the single-host runtime handles pull the replica list and refresh on
miss/failure, which has the same eventual-consistency semantics without
the long-poll machinery).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

CONTROLLER_NAME = "__serve_controller"
_RECONCILE_PERIOD_S = 0.25


def drain_accounting(
    initial: List[int], final: List[int]
) -> Tuple[int, int]:
    """(drained, dropped) from per-victim in-flight counts at drain
    start vs kill time. Booked PER VICTIM — ``max(0, initial - final)``
    drained plus ``final`` dropped — so a victim whose load *rose*
    during the drain window (stale handles kept routing to it) books
    its kill-time in-flight as dropped without subtracting the growth
    from some other victim's drain count. The old aggregate-sum form
    (``drained = sum(initial) - sum(final)``) double-counted exactly
    that case: late arrivals inflated ``final``, deflating every
    victim's drain credit at once — and shed requests never appear in
    either number (they are refused at admission, before any replica
    queue). Every admitted in-flight request lands in exactly one
    bucket."""
    drained = sum(max(0, i - f) for i, f in zip(initial, final))
    dropped = sum(final)
    return drained, dropped


@dataclass
class DeploymentInfo:
    name: str
    cls: Any
    init_args: tuple
    init_kwargs: dict
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    # admission control: cap on outstanding routed requests per handle;
    # 0 = fall back to the serve_max_queued_requests config knob
    max_queued_requests: int = 0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    user_config: Any = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    route_prefix: Optional[str] = None
    version: int = 0


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._replicas: Dict[str, List[Any]] = {}  # name -> actor handles
        self._replica_versions: Dict[str, List[int]] = {}
        self._ping_misses: Dict[bytes, int] = {}  # consecutive health misses
        # deployment -> {replica id -> loaded multiplexed model ids};
        # refreshed from the same batched ping (multiplex routing info)
        self._model_ids: Dict[str, Dict[bytes, List[str]]] = {}
        self._lock = threading.RLock()
        self._shutdown = threading.Event()
        # serve-scope chaos (replica_kill timed faults execute here, on
        # the reconcile tick; None when the plan is inert)
        from ..._private import chaos as chaos_mod

        self._chaos = chaos_mod.engine_for("serve")
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile"
        )
        self._thread.start()

    # -- API (called by serve.run / handles / proxy) -------------------
    def deploy(self, info: DeploymentInfo) -> None:
        with self._lock:
            prev = self._deployments.get(info.name)
            info.version = (prev.version + 1) if prev else 0
            self._deployments[info.name] = info

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            self._deployments.pop(name, None)

    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            return list(self._replicas.get(name, []))

    def get_routing_info(self, name: str) -> Dict[str, Any]:
        """One RPC with everything a handle's refresh needs: the live
        replica set plus the deployment's admission cap."""
        with self._lock:
            info = self._deployments.get(name)
            return {
                "replicas": list(self._replicas.get(name, [])),
                "max_queued_requests": (
                    info.max_queued_requests if info else 0
                ),
            }

    def get_multiplex_map(self, name: str) -> Dict[bytes, List[str]]:
        """replica id -> loaded model ids (router model-affinity info;
        reference: multiplexed_replicas broadcast via LongPollHost)."""
        with self._lock:
            return {
                rid: list(ids)
                for rid, ids in self._model_ids.get(name, {}).items()
            }

    def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                n: {
                    "num_replicas": d.num_replicas,
                    "live_replicas": len(self._replicas.get(n, [])),
                    "route_prefix": d.route_prefix,
                    "version": d.version,
                }
                for n, d in self._deployments.items()
            }

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return {
                d.route_prefix: n
                for n, d in self._deployments.items()
                if d.route_prefix
            }

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._lock:
            names = list(self._deployments)
            self._deployments.clear()
        for name in names:
            self._scale_to(name, None, 0)

    def ready(self) -> bool:
        """True when every deployment has its target replica count."""
        with self._lock:
            return all(
                len(self._replicas.get(n, [])) >= d.num_replicas
                for n, d in self._deployments.items()
            )

    # -- reconcile ----------------------------------------------------
    def _reconcile_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
            except Exception:
                import traceback

                traceback.print_exc()
            self._autoscale()
            try:
                self._run_chaos()
            except Exception:
                pass
            self._shutdown.wait(_RECONCILE_PERIOD_S)

    def _run_chaos(self) -> None:
        """Execute due serve-scope timed faults (replica_kill): victim
        drawn from the serve rng over the deployment's live set, so a
        fixed seed kills the same replica index at the same tick."""
        eng = self._chaos
        if eng is None or not eng.timed:
            return
        import ray_tpu

        for fault in eng.due_faults():
            if fault.kind != "replica_kill":
                eng.consume(fault, fault.count - fault.fired)
                continue
            with self._lock:
                live = list(self._replicas.get(fault.arg, []))
            if not live:
                eng.defer(fault)
                continue
            idx = eng.rng.randrange(len(live))
            victim = live[idx]
            eng.record(
                "replica_kill", deployment=fault.arg, victim_index=idx,
                at_s=fault.at,
            )
            eng.consume(fault)
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass

    def chaos_snapshot(self) -> Dict[str, Any]:
        """The serve chaos engine's state (fired events, pending timed
        schedule) — the determinism probe for seeded serve soaks."""
        return self._chaos.snapshot() if self._chaos is not None else {}

    def _reconcile_once(self) -> None:
        import ray_tpu

        with self._lock:
            targets = dict(self._deployments)
        for name, info in targets.items():
            live = self._replicas.get(name, [])
            versions = self._replica_versions.get(name, [])
            # health checks: ONE parallel ping round per deployment per
            # reconcile (was one blocking round-trip per replica —
            # O(replicas) control latency, r1 Weak finding). A slow
            # replica is only retired after 3 consecutive missed pings
            # (reference: health_check_failure_threshold).
            refs = [actor.stats.remote() for actor in live]
            done, _ = ray_tpu.wait(  # graftlint: disable=GL017 — control-plane health sweep on a fixed cadence, no request deadline exists here
                refs, num_returns=len(refs), timeout=5.0
            ) if refs else ([], [])
            done_set = set(done)
            alive, alive_vers = [], []
            victims: List[Any] = []
            ongoing_sum = queued_sum = 0
            with self._lock:
                model_map = self._model_ids.setdefault(name, {})
            for actor, ver, ref in zip(live, versions, refs):
                rid = actor._actor_id.binary()
                if ref in done_set:
                    try:
                        stats = ray_tpu.get(ref)
                        mux = stats.get("multiplexed_model_ids") or []
                        with self._lock:
                            if mux or rid in model_map:
                                model_map[rid] = list(mux)
                        ongoing_sum += int(stats.get("ongoing", 0))
                        queued_sum += int(stats.get("queued", 0))
                        healthy = True
                        self._ping_misses.pop(rid, None)
                    except Exception:
                        healthy = False
                else:
                    misses = self._ping_misses.get(rid, 0) + 1
                    self._ping_misses[rid] = misses
                    healthy = misses < 3
                if not healthy:
                    self._ping_misses.pop(rid, None)
                    continue
                # version bump (redeploy): retire old-code replicas —
                # deferred past the routing-table update so they drain
                # in-flight requests instead of dying mid-request
                if ver == info.version:
                    alive.append(actor)
                    alive_vers.append(ver)
                else:
                    victims.append(actor)
            while len(alive) < info.num_replicas:
                actor = self._start_replica(info)
                alive.append(actor)
                alive_vers.append(info.version)
            while len(alive) > info.num_replicas:
                victims.append(alive.pop())
                alive_vers.pop()
            with self._lock:
                self._replicas[name] = alive
                self._replica_versions[name] = alive_vers
                alive_rids = {a._actor_id.binary() for a in alive}
                for rid in list(model_map):
                    if rid not in alive_rids:
                        del model_map[rid]
            # routing table now excludes the victims: drain, then kill
            self._retire_replicas(name, victims)
            from . import observability as obs

            obs.set_deployment_gauges(
                name, ongoing_sum, queued_sum, len(alive)
            )
        # GC deleted deployments
        with self._lock:
            for name in list(self._replicas):
                if name not in targets:
                    self._scale_to(name, None, 0)
            for name in list(self._model_ids):
                if name not in targets:
                    del self._model_ids[name]
            live_rids = {
                a._actor_id.binary()
                for actors in self._replicas.values()
                for a in actors
            }
        # miss counters only for replicas that still exist (retired
        # generations would otherwise leak entries forever). Pruned
        # outside the lock: _ping_misses is reconcile-thread-only state,
        # only _replicas needs self._lock.
        for rid in list(self._ping_misses):
            if rid not in live_rids:
                del self._ping_misses[rid]

    def _start_replica(self, info: DeploymentInfo):
        import ray_tpu
        from .replica import Replica

        opts = dict(info.ray_actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        opts["max_concurrency"] = max(2, info.max_ongoing_requests)
        replica_cls = ray_tpu.remote(Replica)
        actor = replica_cls.options(**opts).remote(
            info.name, info.cls, info.init_args, info.init_kwargs, info.user_config
        )
        return actor

    def _retire_replicas(self, name: str, victims: List[Any]) -> None:
        """Graceful teardown: drain in-flight requests, then kill.

        Callers must have removed the victims from self._replicas FIRST
        (so routers stop sending new work), though handles cache the
        replica list for up to a second — the drain window absorbs that
        too. Polls each victim's queue_len (ongoing + batch-parked, the
        same load signal the router uses) until idle or
        RAY_TPU_SERVE_DRAIN_TIMEOUT_S elapses; whatever is still
        in-flight at the deadline is dropped with the kill. Both
        outcomes are counted (drained vs dropped) so a chaos run can
        quantify graceful degradation.
        """
        import os

        import ray_tpu

        from . import observability as obs

        if not victims:
            return

        def _load(actor) -> int:
            try:
                return int(ray_tpu.get(actor.queue_len.remote(), timeout=2.0))  # graftlint: disable=GL017 — retirement drain probe; a dead replica must read as empty quickly
            except Exception:
                return 0  # dead/unreachable: nothing left to drain

        timeout_s = float(os.environ.get("RAY_TPU_SERVE_DRAIN_TIMEOUT_S", "5"))
        deadline = time.monotonic() + timeout_s
        initial = [_load(a) for a in victims]
        pending = [a for a, n in zip(victims, initial) if n > 0]
        while pending and time.monotonic() < deadline:
            pending = [a for a in pending if _load(a) > 0]
            if pending:
                time.sleep(0.05)
        still_pending = {id(a) for a in pending}
        final = [
            _load(a) if id(a) in still_pending else 0 for a in victims
        ]
        drained, dropped = drain_accounting(initial, final)
        obs.count_drained(name, drained)
        obs.count_dropped(name, dropped)
        for actor in victims:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass

    def _scale_to(self, name: str, info, n: int) -> None:
        with self._lock:
            live = self._replicas.get(name, [])
            keep, drop = live[:n], live[n:]
            if n == 0:
                self._replicas.pop(name, None)
                self._replica_versions.pop(name, None)
            else:
                self._replicas[name] = keep
                self._replica_versions[name] = self._replica_versions.get(name, [])[:n]
        self._retire_replicas(name, drop)

    # -- autoscaling ---------------------------------------------------
    def _autoscale(self) -> None:
        """Target-ongoing-requests autoscaling (reference:
        serve/_private/autoscaling_state.py + autoscaling_policy.py:
        desired = ceil(total_ongoing / target_per_replica), clamped)."""
        import math

        import ray_tpu

        with self._lock:
            targets = {
                n: d for n, d in self._deployments.items() if d.autoscaling_config
            }
        for name, info in targets.items():
            cfg = info.autoscaling_config
            replicas = self.get_replicas(name)
            if not replicas:
                continue
            try:
                loads = ray_tpu.get(  # graftlint: disable=GL017 — autoscaler metrics poll on its own cadence, not a request path
                    [r.queue_len.remote() for r in replicas], timeout=5.0
                )
            except Exception:
                continue
            total = sum(loads)
            target_per = cfg.get("target_ongoing_requests", 2)
            desired = max(1, math.ceil(total / max(target_per, 1e-9)))
            desired = min(
                cfg.get("max_replicas", 1), max(cfg.get("min_replicas", 1), desired)
            )
            if desired != info.num_replicas:
                with self._lock:
                    if name in self._deployments:
                        self._deployments[name].num_replicas = desired
