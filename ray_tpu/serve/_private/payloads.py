"""Zero-copy serve payload plane: large bodies ride the object plane.

The serve hot path used to pickle every request/response inline through
the hub: the handle shipped args as VAL_INLINE actor-call blobs and
results came back the same way, so a 1 MiB body paid multiple pickle
copies plus two rides through the hub reactor. This codec tiers the
transport by size ("The Big Send-off" argument): bodies at or below
RAY_TPU_SERVE_INLINE_MAX (config "serve_inline_max", default 64 KiB)
keep the inline path — one hub round-trip beats a put + resolve for
small payloads — while anything STRICTLY larger spills onto the PR 6
direct object plane:

- Request side (handle.DeploymentHandle._route): oversized bytes /
  bytearray / memoryview / ndarray values — top-level args/kwargs and
  one level inside dict args, which covers the ingress request dict's
  "body" — are put via the object plane (serialization.RawPayload makes
  the bytes ride out-of-band: ONE memcpy into shm, never a pickle
  stream) and replaced by PayloadRef markers. The spilled ids ride the
  actor call's arg_deps (the hub pins them while the call is in
  flight), and the owned twin refs live on the DeploymentResponse so
  ownership GC frees the segment when the caller drops the response.
- Replica side (replica.Replica.handle_request): markers and top-level
  ObjectRefs (composition args) resolve in ONE bulk client.get before
  the user callable runs; raw payloads arrive as zero-copy memoryviews
  over the mapped segment. @serve.batch targets defer resolution to the
  batch queue so ALL members of a batch share one fetch
  (batching._BatchQueue._loop).
- Response side: results larger than the threshold return wrapped in
  RawPayload, so the ordinary task-return path stores them as shm
  segments. DeploymentResponse.result() fetches with
  client.get(oneshot=True): local segments map zero-copy; remote ones
  pull straight from the owner's object agent
  (object_agent.pull_segment_bytes + object_store.decode_segment_bytes)
  without the full CoreClient install/replica/ref-count dance; any
  transfer error falls back to the standard fetch matrix, ending in the
  hub relay (chaos-safe: a mid-transfer agent death degrades to the
  relay, never fails the request).

Spans: the handle emits serve.payload_put around the spill and the
replica/batch loop emits serve.payload_fetch around the bulk resolve;
both stages are in tracing.STAGE_PRECEDENCE so analyze_trace partitions
stay exact.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from ..._private.serialization import RawPayload, materialize_raw
from ...object_ref import ObjectRef

# bulk fetches issued by THIS process — one per resolve call that hit
# the wire, NOT one per payload. Tests assert a batch of N spilled
# requests bumps this once (the members-share-one-fetch contract).
FETCH_CALLS = 0


def inline_max() -> int:
    """Current spill threshold in bytes (values <= 0 disable spilling).
    Read through the config module attribute so a hub-triggered
    config.reload() (fresh env overrides) is observed."""
    from ..._private import config as _config

    try:
        return int(_config.RAY_TPU_CONFIG.get("serve_inline_max", 64 * 1024))
    except (TypeError, ValueError):
        return 64 * 1024


class PayloadRef:
    """Marker standing in for one spilled payload inside a routed
    call's args: carries the object id (for the replica's bulk resolve
    and the dispatch's arg_deps pin) and the byte size (for spans).
    Pickles by reference — this module is importable in every
    process."""

    __slots__ = ("oid_bytes", "nbytes")

    def __init__(self, oid_bytes: bytes, nbytes: int):
        self.oid_bytes = oid_bytes
        self.nbytes = nbytes

    def __reduce__(self):
        return (PayloadRef, (self.oid_bytes, self.nbytes))

    def __repr__(self):
        return f"PayloadRef({self.oid_bytes.hex()}, {self.nbytes}B)"


def _numpy():
    try:
        import numpy as np

        return np
    except Exception:
        return None


def _payload_size(v: Any) -> int:
    """Spillable size of v, or -1 when v is not a raw payload."""
    if isinstance(v, (bytes, bytearray)):
        return len(v)
    if isinstance(v, memoryview):
        return v.nbytes
    np = _numpy()
    if np is not None and isinstance(v, np.ndarray) and v.dtype != object:
        return int(v.nbytes)
    return -1


# ---------------------------------------------------------------- spill
def spill_args(
    args: tuple, kwargs: dict
) -> Tuple[tuple, dict, List[ObjectRef], List[bytes], int]:
    """Replace oversized raw payloads with PayloadRef markers, putting
    the bytes via the object plane. Returns (args, kwargs, holds,
    dep_ids, spilled_bytes):

    - holds: OWNED twin refs for fresh spills — the caller parks them
      on the DeploymentResponse so ownership GC frees the segments when
      the response is dropped (retry re-sends keep working meanwhile).
    - dep_ids: EVERY payload id in the call — fresh spills and
      pre-existing markers (a _reroute re-send) — for the dispatch's
      arg_deps, which the hub pins while the call is in flight.
    """
    limit = inline_max()
    holds: List[ObjectRef] = []
    dep_ids: List[bytes] = []
    spilled = [0]
    client_box: List[Any] = []

    def spill_one(v: Any, n: int) -> Any:
        if not client_box:
            from ..._private import worker

            client_box.append(worker._client)
        client = client_box[0]
        if client is None:
            return v  # no runtime: leave inline (e.g. bare unit tests)
        np = _numpy()
        if np is not None and isinstance(v, np.ndarray):
            # arrays keep dtype/shape: put the array itself — protocol-5
            # out-of-band pickling already makes the data zero-copy;
            # force_shm keeps the 64-100 KiB window off the inline path
            obj = v if v.flags["C_CONTIGUOUS"] else np.ascontiguousarray(v)
            oid = client.put_value(obj, force_shm=True, cache=False)
        else:
            oid = client.put_value(RawPayload(v), cache=False)
        holds.append(ObjectRef(oid, _owned=True))
        dep_ids.append(oid.binary())
        spilled[0] += n
        return PayloadRef(oid.binary(), n)

    def maybe_spill(v: Any) -> Any:
        if isinstance(v, PayloadRef):
            dep_ids.append(v.oid_bytes)  # retry re-send: re-pin only
            return v
        if limit > 0:
            n = _payload_size(v)
            if n > limit:
                return spill_one(v, n)
        return v

    def walk(v: Any) -> Any:
        v2 = maybe_spill(v)
        if v2 is not v:
            return v2
        if type(v) is dict:
            # one level into plain dicts: the ingress request dict
            # carries its body under "body"
            out = None
            for k, item in v.items():
                item2 = maybe_spill(item)
                if item2 is not item:
                    if out is None:
                        out = dict(v)
                    out[k] = item2
            return v if out is None else out
        return v

    args = tuple(walk(a) for a in args)
    kwargs = {k: walk(v) for k, v in kwargs.items()}
    return args, kwargs, holds, dep_ids, spilled[0]


# -------------------------------------------------------------- resolve
def _scan_value(v: Any, want: Dict[bytes, int]) -> bool:
    """Record every payload/ref id reachable from v (top level + one
    dict level); True when v needs a substitution pass."""
    if isinstance(v, PayloadRef):
        want[v.oid_bytes] = v.nbytes
        return True
    if isinstance(v, ObjectRef):
        want.setdefault(v._id.binary(), 0)
        return True
    if type(v) is dict:
        hit = False
        for item in v.values():
            if isinstance(item, PayloadRef):
                want[item.oid_bytes] = item.nbytes
                hit = True
            elif isinstance(item, ObjectRef):
                want.setdefault(item._id.binary(), 0)
                hit = True
        return hit
    return False


def _sub_value(v: Any, got: Dict[bytes, Any]) -> Any:
    if isinstance(v, PayloadRef):
        return materialize_raw(got[v.oid_bytes])
    if isinstance(v, ObjectRef):
        return got[v._id.binary()]
    if type(v) is dict:
        out = dict(v)
        for k, item in v.items():
            if isinstance(item, PayloadRef):
                out[k] = materialize_raw(got[item.oid_bytes])
            elif isinstance(item, ObjectRef):
                out[k] = got[item._id.binary()]
        return out
    return v


def _bulk_fetch(want: Dict[bytes, int]) -> Dict[bytes, Any]:
    global FETCH_CALLS
    from ..._private import worker
    from ..._private.ids import ObjectID

    client = worker.get_client()
    FETCH_CALLS += 1
    got: Dict[bytes, Any] = {}
    remote: List[bytes] = []
    store = getattr(client, "store", None)
    for b, nbytes in want.items():
        # Payload ids (nbytes > 0) are arg-deps-gated: the hub admitted
        # this task only after every payload was READY, so a same-node
        # segment is fully written — map it straight off the store and
        # skip the hub GET round trip. Composition ObjectRefs
        # (nbytes == 0) get no such guarantee (their producer may still
        # be writing the segment file), so they always go through get().
        if nbytes > 0 and store is not None:
            try:
                name = b.hex()
                if store.contains(name):
                    got[b] = store.get(name)
                    continue
            except Exception:
                pass
        remote.append(b)
    if remote:
        values = client.get([ObjectID(b) for b in remote], oneshot=True)
        got.update(zip(remote, values))
    return got


def resolve_args(args: tuple, kwargs: dict) -> Tuple[tuple, dict, int, int]:
    """Replica-side: substitute every PayloadRef (zero-copy memoryview)
    and top-level ObjectRef (composition arg) through ONE bulk get.
    Returns (args, kwargs, n_fetched, payload_bytes)."""
    want: Dict[bytes, int] = {}
    arg_hits = [_scan_value(a, want) for a in args]
    kw_hits = {k: _scan_value(v, want) for k, v in kwargs.items()}
    if not want:
        return args, kwargs, 0, 0
    got = _bulk_fetch(want)
    args = tuple(
        _sub_value(a, got) if hit else a for a, hit in zip(args, arg_hits)
    )
    kwargs = {
        k: (_sub_value(v, got) if kw_hits[k] else v) for k, v in kwargs.items()
    }
    return args, kwargs, len(want), sum(want.values())


def has_payload_refs(items: List[Any]) -> bool:
    """Cheap probe: does any batch member carry a marker/ref?"""
    for v in items:
        if isinstance(v, (PayloadRef, ObjectRef)):
            return True
        if type(v) is dict and any(
            isinstance(i, (PayloadRef, ObjectRef)) for i in v.values()
        ):
            return True
    return False


def resolve_batch_items(items: List[Any]) -> Tuple[List[Any], int, int]:
    """Batch-queue variant of resolve_args: EVERY member's payloads
    resolve through one shared fetch — N batched 1 MiB requests cost
    one get round-trip, not N."""
    want: Dict[bytes, int] = {}
    hits = [_scan_value(it, want) for it in items]
    if not want:
        return items, 0, 0
    got = _bulk_fetch(want)
    items = [
        _sub_value(it, got) if hit else it for it, hit in zip(items, hits)
    ]
    return items, len(want), sum(want.values())


def is_batch_target(target: Any) -> bool:
    """@serve.batch callables defer marker resolution to the batch
    queue (one shared fetch per batch, not one per member)."""
    if getattr(target, "_is_serve_batch", False):
        return True
    call = getattr(target, "__call__", None)
    return bool(getattr(call, "_is_serve_batch", False))


# ------------------------------------------------------------- response
def wrap_result(result: Any) -> Any:
    """Replica-side: wrap an oversized raw result so the task-return
    path stores it as a shm segment (encode_value never inlines a
    RawPayload) instead of pickling it back through the hub.
    memoryviews ALWAYS convert — they don't pickle: big ones wrap
    zero-copy, small ones collapse to bytes. ndarray results already
    ride out-of-band via the normal return path and stay untouched."""
    limit = inline_max()
    if isinstance(result, memoryview):
        if limit > 0 and result.nbytes > limit:
            return RawPayload(result)
        return bytes(result)
    if limit <= 0:
        return result
    if isinstance(result, (bytes, bytearray)) and len(result) > limit:
        return RawPayload(result)
    from ..response import Response as ServeResponse

    if isinstance(result, ServeResponse):
        body = result.body
        nbytes = (
            body.nbytes
            if isinstance(body, memoryview)
            else len(body) if isinstance(body, (bytes, bytearray)) else -1
        )
        new_body = None
        if nbytes > limit:
            new_body = RawPayload(body)
        elif isinstance(body, memoryview):
            new_body = bytes(body)
        if new_body is not None:
            import copy

            result = copy.copy(result)
            result.body = new_body
    return result


def unwrap_result(value: Any) -> Any:
    """Consumer-side (DeploymentResponse / proxy): collapse the
    RawPayload shapes to memoryviews. Large bodies STAY memoryviews —
    that is the zero-copy contract; callers needing bytes copy
    explicitly (serve.Response.body_bytes does)."""
    value = materialize_raw(value)
    from ..response import Response as ServeResponse

    if isinstance(value, ServeResponse):
        body = materialize_raw(value.body)
        if body is not value.body:
            value.body = body
    return value
