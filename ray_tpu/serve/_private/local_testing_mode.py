"""Local testing mode: run a Serve app fully in-process, no cluster.

Parity: python/ray/serve/_private/local_testing_mode.py — the reference
instantiates each deployment's callable directly and wires handles to
plain method calls so unit tests run without any actors. Same here:
``serve.run(app, local_testing_mode=True)`` builds the bound graph
in-process; handles become `_LocalHandle`s whose responses resolve
synchronously (composition, multiplexing, streaming, and async methods
all work — just without processes or the controller).
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Dict


class _LocalResponse:
    """DeploymentResponse stand-in. Async results stay lazy: a pending
    coroutine is awaited by ``await resp`` (async callers) or run on a
    fresh loop by ``.result()`` (sync callers) — so local handles work
    from both worlds, like the real DeploymentHandle."""

    def __init__(self, value: Any = None, exc: BaseException = None, coro=None):
        self._value = value
        self._exc = exc
        self._coro = coro

    def _resolve_sync(self) -> None:
        if self._coro is None:
            return
        coro, self._coro = self._coro, None
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            raise RuntimeError(
                "local handle .result() called inside a running event "
                "loop; use `await response` instead"
            )
        try:
            self._value = asyncio.run(coro)
        except BaseException as exc:
            self._exc = exc

    def result(self, timeout_s: float = None) -> Any:
        self._resolve_sync()
        if self._exc is not None:
            raise self._exc
        return self._value

    def _to_object_ref(self):
        return self.result()

    def __await__(self):
        async def _get():
            if self._coro is not None:
                coro, self._coro = self._coro, None
                try:
                    self._value = await coro
                except BaseException as exc:
                    self._exc = exc
            if self._exc is not None:
                raise self._exc
            return self._value

        return _get().__await__()


class _LocalResponseGenerator:
    """Streamed response: sync generators iterate directly; async
    generators drain on a fresh loop for sync callers and natively for
    async callers (the real replica supports both — replica.py
    handle_request_streaming)."""

    def __init__(self, gen=None, agen=None, coro=None):
        self._gen = gen
        self._agen = agen
        self._coro = coro  # plain async method under stream=True

    def __iter__(self):
        if self._coro is not None:
            coro, self._coro = self._coro, None
            yield asyncio.run(coro)
            return
        if self._agen is not None:
            async def _drain(agen=self._agen):
                return [item async for item in agen]

            yield from asyncio.run(_drain())
            return
        yield from self._gen

    async def __aiter__(self):
        if self._coro is not None:
            coro, self._coro = self._coro, None
            yield await coro
            return
        if self._agen is not None:
            async for item in self._agen:
                yield item
            return
        for item in self._gen:
            yield item


class _LocalHandle:
    """DeploymentHandle stand-in bound to one in-process instance."""

    def __init__(self, instance, method_name: str = "__call__"):
        self._instance = instance
        self._method = method_name
        self._stream = False
        self._model_id = ""

    def options(self, *, method_name=None, stream=None,
                multiplexed_model_id=None) -> "_LocalHandle":
        h = _LocalHandle(self._instance, method_name or self._method)
        h._stream = self._stream if stream is None else stream
        h._model_id = (
            self._model_id if multiplexed_model_id is None
            else multiplexed_model_id
        )
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _LocalMethodCaller(self, name)

    def remote(self, *args, **kwargs):
        return self._call(self._method, args, kwargs)

    def _call(self, method: str, args, kwargs):
        from ..multiplex import _model_id_ctx

        args = tuple(
            a.result() if isinstance(a, _LocalResponse) else a for a in args
        )
        kwargs = {
            k: (v.result() if isinstance(v, _LocalResponse) else v)
            for k, v in kwargs.items()
        }
        target = (
            self._instance
            if method == "__call__" and not inspect.isclass(self._instance)
            else getattr(self._instance, method)
        )
        token = _model_id_ctx.set(self._model_id)
        try:
            result = target(*args, **kwargs)
            if self._stream:
                if inspect.isasyncgen(result):
                    return _LocalResponseGenerator(agen=result)
                if inspect.isgenerator(result):
                    return _LocalResponseGenerator(gen=result)
                if inspect.iscoroutine(result):
                    # one-item stream, resolved lazily at iteration so
                    # errors surface at consumption and async callers
                    # can drive it on their own loop
                    return _LocalResponseGenerator(coro=result)
                return _LocalResponseGenerator(gen=iter([result]))
            if inspect.iscoroutine(result):
                # body runs later (at await/result): re-enter the model
                # id context around the actual execution
                async def _with_ctx(coro=result, mid=self._model_id):
                    tok = _model_id_ctx.set(mid)
                    try:
                        return await coro
                    finally:
                        _model_id_ctx.reset(tok)

                return _LocalResponse(coro=_with_ctx())
            return _LocalResponse(result)
        except BaseException as exc:  # surfaced on .result()
            return _LocalResponse(exc=exc)
        finally:
            _model_id_ctx.reset(token)


class _LocalMethodCaller:
    def __init__(self, handle: _LocalHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._call(self._method, args, kwargs)


def run_local(app) -> _LocalHandle:
    """Instantiate the bound graph in-process, depth-first, replacing
    nested Applications with local handles (composition parity)."""
    instances: Dict[str, Any] = {}

    def build(a) -> _LocalHandle:
        d = a.deployment
        if d.name not in instances:
            args = tuple(
                build(x) if _is_application(x) else x for x in a.args
            )
            kwargs = {
                k: (build(v) if _is_application(v) else v)
                for k, v in a.kwargs.items()
            }
            target = d.func_or_class
            if inspect.isclass(target):
                instance = target(*args, **kwargs)
            elif callable(target):
                instance = target  # function deployment
            else:
                raise TypeError(f"cannot deploy {target!r}")
            if d.user_config is not None and hasattr(instance, "reconfigure"):
                instance.reconfigure(d.user_config)
            instances[d.name] = instance
        return _LocalHandle(instances[d.name])

    return build(app)


def _is_application(x) -> bool:
    from .. import Application

    return isinstance(x, Application)
