"""Serve-plane observability: request spans + per-route SLO metrics.

Parity: python/ray/serve/_private/metrics_utils.py + the request-context
propagation in serve/_private/replica.py — the reference stamps every
request with a RequestContext and exports per-deployment counters and
latency histograms through the metrics agent. Here both halves ride the
runtime's EXISTING planes (no new message types):

**Spans** extend the PR 8 runtime-trace catalog into the request path —
``serve.proxy_recv`` -> ``serve.route`` -> (task-layer submit/execute
spans) -> ``serve.queue_wait`` -> ``serve.execute`` (with
``serve.batch_wait`` / ``serve.multiplex_swap`` nested inside) ->
``serve.response_return``. Sampling is the same head gate as every
other runtime span (``RAY_TPU_TRACE_SAMPLE`` / ``RAY_TPU_TRACING``,
default 0 = no work at all), the trace context crosses the
proxy->replica hop inside the ordinary actor-call payload, and finished
spans ship as the existing ``SPAN_RECORD`` message.

**Metrics** are ordinary ``METRIC_RECORD`` series tagged
``(deployment, route)`` aggregating in the hub registry, so they land
in ``snapshot()`` / ``prometheus_text()`` / the dashboard for free and
the hub's ``list_state("serve")`` branch can pivot them into one row
per deployment.

Every emitter here is fire-and-forget and exception-proof: serving must
never fail because observability did.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

from ..._private import protocol as P
from ...util import tracing as _tracing

# Latency boundaries sized for serving (sub-ms cache hits through
# multi-second LLM generations). Shared by every serve latency series so
# the hub can merge per-route histograms bucket-by-bucket.
LATENCY_BOUNDS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
# Batch occupancy (actual/max batch size) in (0, 1].
BATCH_RATIO_BOUNDS: Tuple[float, ...] = (
    0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0,
)

REQUESTS_TOTAL = "ray_tpu_serve_requests_total"
LATENCY_HIST = "ray_tpu_serve_request_latency_seconds"
ERRORS_TOTAL = "ray_tpu_serve_errors_total"
TIMEOUTS_TOTAL = "ray_tpu_serve_timeouts_total"
ONGOING_GAUGE = "ray_tpu_serve_ongoing_requests"
QUEUE_DEPTH_GAUGE = "ray_tpu_serve_queue_depth"
REPLICA_GAUGE = "ray_tpu_serve_replicas"
BATCH_SIZE_HIST = "ray_tpu_serve_batch_size"
BATCH_RATIO_HIST = "ray_tpu_serve_batch_ratio"
MODEL_SWAPS_TOTAL = "ray_tpu_serve_model_swaps_total"
DRAINED_TOTAL = "ray_tpu_serve_drained_requests_total"
DROPPED_TOTAL = "ray_tpu_serve_dropped_requests_total"
SHED_TOTAL = "ray_tpu_serve_shed_total"
EXPIRED_TOTAL = "ray_tpu_serve_expired_requests_total"
EJECTIONS_TOTAL = "ray_tpu_serve_ejections_total"

# The deployment this replica process hosts (set by Replica.__init__):
# lets @serve.batch queues — which only see the bound user function —
# tag their metrics without threading the name through the decorator.
_current_deployment: str = ""


def set_current_deployment(name: str) -> None:
    global _current_deployment
    _current_deployment = name


def current_deployment() -> str:
    return _current_deployment


def _client():
    from ..._private import worker

    if not worker.is_initialized():
        return None
    try:
        return worker.get_client()
    except Exception:
        return None


# ------------------------------------------------------------------ spans
def sampling_live() -> bool:
    """One cheap gate for the serve hot path: an ambient trace context
    (this request is already traced) or this process head-samples."""
    if _tracing.current_context() is not None:
        return True
    client = _client()
    return client is not None and client._trace_on


def begin_trace() -> Optional[Tuple[str, Optional[str]]]:
    """(trace_id, parent_span_id) for one serve request, or None when
    unsampled. Inherits the ambient context (a traced caller — e.g. a
    composed deployment calling a child handle) before head-sampling a
    fresh trace, mirroring CoreClient._trace_begin."""
    ctx = _tracing.current_context()
    if ctx is not None:
        return ctx
    client = _client()
    if client is None or not client._trace_on:
        return None
    import random

    r = client._trace_rate
    if r >= 1.0 or random.random() < r:
        return (_tracing.new_span_id(), None)
    return None


def emit_span(
    name: str,
    stage: str,
    trace_id: str,
    parent_id: Optional[str],
    t0_mono: float,
    t1_mono: float,
    span_id: Optional[str] = None,
    **attrs: Any,
) -> Optional[str]:
    """Ship one finished serve span on the existing SPAN_RECORD path.
    Returns the span id (so callers can parent further spans), or None
    when no client is connected. Record built inline — same fast shape
    as CoreClient._trace_emit, no intermediate attr-dict copies."""
    client = _client()
    if client is None:
        return None
    a: Dict[str, str] = {"stage": stage}
    for k, v in attrs.items():
        a[k] = str(v)
    sid = span_id or _tracing.new_span_id()
    rec = {
        "name": name,
        "trace_id": trace_id,
        "span_id": sid,
        "parent_id": parent_id,
        "start": _tracing.wall_at(t0_mono),
        "end": _tracing.wall_at(t1_mono),
        "pid": client._pid,
        "node_id": client.node_id,
        "attrs": a,
    }
    try:
        client.send_async(P.SPAN_RECORD, rec)
    except Exception:
        pass
    return sid


def mono_at_wall(wall: float, now_mono: Optional[float] = None) -> float:
    """Invert tracing.wall_at for a wall stamp taken in ANOTHER process
    on the same host: the monotonic instant (in THIS process's clock)
    that renders to that wall time. Lets the replica open its
    serve.queue_wait span at the handle's enqueue moment."""
    now = time.monotonic() if now_mono is None else now_mono
    return now - max(0.0, _tracing.wall_at(now) - wall)


# ----------------------------------------------------------------- metrics
def _record(name: str, mtype: str, value: float, op: str,
            tags: Tuple[Tuple[str, str], ...],
            boundaries: Tuple[float, ...] = (),
            description: str = "") -> None:
    client = _client()
    if client is None:
        return
    payload = {
        "name": name,
        "type": mtype,
        "description": description,
        "value": float(value),
        "tags": tags,
        "op": op,
    }
    if boundaries:
        payload["boundaries"] = boundaries
    try:
        client.send_async(P.METRIC_RECORD, payload)
    except Exception:
        pass


def _tags(deployment: str, route: str = "") -> Tuple[Tuple[str, str], ...]:
    # sorted tuple-of-pairs, matching util/metrics.Metric._record so the
    # hub registry keys line up regardless of which path recorded first
    return (("deployment", deployment), ("route", route))


def count_request(deployment: str, route: str = "") -> None:
    _record(REQUESTS_TOTAL, "counter", 1.0, "add", _tags(deployment, route),
            description="serve requests routed")


def observe_latency(deployment: str, route: str, seconds: float) -> None:
    _record(LATENCY_HIST, "histogram", seconds, "observe",
            _tags(deployment, route), boundaries=LATENCY_BOUNDS,
            description="serve end-to-end request latency")


def count_error(deployment: str, route: str = "") -> None:
    _record(ERRORS_TOTAL, "counter", 1.0, "add", _tags(deployment, route),
            description="serve requests failed")


def count_timeout(deployment: str, route: str = "") -> None:
    _record(TIMEOUTS_TOTAL, "counter", 1.0, "add", _tags(deployment, route),
            description="serve requests timed out")


def observe_batch(deployment: str, batch_size: int, max_batch_size: int) -> None:
    """One executed batch: absolute size + occupancy ratio. Efficiency
    (= mean actual/max) is the ratio histogram's sum/count."""
    t = _tags(deployment)
    _record(BATCH_SIZE_HIST, "histogram", float(batch_size), "observe", t,
            boundaries=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
            description="serve batch sizes")
    _record(BATCH_RATIO_HIST, "histogram",
            batch_size / float(max_batch_size or 1), "observe", t,
            boundaries=BATCH_RATIO_BOUNDS,
            description="serve batch occupancy (actual/max batch size)")


def count_model_swap(deployment: str) -> None:
    _record(MODEL_SWAPS_TOTAL, "counter", 1.0, "add", _tags(deployment),
            description="multiplexed model loads (LRU misses)")


def set_deployment_gauges(deployment: str, ongoing: int, queued: int,
                          replicas: int) -> None:
    """Controller-side, once per reconcile: live load per deployment."""
    t = _tags(deployment)
    _record(ONGOING_GAUGE, "gauge", float(ongoing), "set", t,
            description="requests executing across replicas")
    _record(QUEUE_DEPTH_GAUGE, "gauge", float(queued), "set", t,
            description="requests parked in replica batch queues")
    _record(REPLICA_GAUGE, "gauge", float(replicas), "set", t,
            description="live replicas")


def count_drained(deployment: str, n: int) -> None:
    if n > 0:
        _record(DRAINED_TOTAL, "counter", float(n), "add", _tags(deployment),
                description="in-flight requests drained before replica teardown")


def count_dropped(deployment: str, n: int) -> None:
    if n > 0:
        _record(DROPPED_TOTAL, "counter", float(n), "add", _tags(deployment),
                description="in-flight requests dropped at replica teardown")


# Shed / expired / ejected are DISJOINT from drained / dropped by
# construction: a shed request never reaches a replica (refused at
# admission), an expired one is dropped before its user callable runs,
# and both are also disjoint from each other — the router sheds before
# it stamps a deadline. Drain accounting at teardown therefore only
# ever sees admitted, unexpired in-flight work.
def count_shed(deployment: str, route: str = "") -> None:
    _record(SHED_TOTAL, "counter", 1.0, "add", _tags(deployment, route),
            description="requests shed at admission (max_queued_requests)")


def count_expired(deployment: str, route: str = "") -> None:
    _record(EXPIRED_TOTAL, "counter", 1.0, "add", _tags(deployment, route),
            description="requests whose deadline passed before execute")


def count_ejection(deployment: str) -> None:
    _record(EJECTIONS_TOTAL, "counter", 1.0, "add", _tags(deployment),
            description="replicas ejected from the router after "
                        "consecutive failures")
