"""DeploymentHandle: the caller-side router.

Parity: python/ray/serve/handle.py + _private/router.py:321 +
replica_scheduler/pow_2_scheduler.py:52 — requests route to the replica
with the shorter queue among two random choices (power of two choices),
tracked by caller-side outstanding counts and corrected by periodic
replica-list refresh. ``.remote()`` returns a DeploymentResponse future
(composable: passing a response as an argument chains on its result).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

_REFRESH_PERIOD_S = 1.0


def _rid(replica) -> bytes:
    """Stable identity of a replica actor across handle refreshes."""
    return replica._actor_id.binary()


class DeploymentResponse:
    """Future for one request (parity: serve.handle.DeploymentResponse).

    Holds the routing context so a request that landed on a replica torn
    down mid-flight (redeploy, scale-down, crash) is transparently
    re-routed — the reference's router likewise reschedules on replica
    death rather than surfacing ActorDiedError to the caller.
    """

    _MAX_RETRIES = 3

    def __init__(self, ref, handle=None, method=None, args=(), kwargs=None):
        self._ref = ref
        self._handle = handle
        self._method = method
        self._args = args
        self._kwargs = kwargs or {}
        # owned twin refs of payloads spilled onto the object plane for
        # this request (serve/_private/payloads.py). Living here — not
        # on the task ref — they survive _reroute's ref swap, and
        # ownership GC frees the segments when the caller drops the
        # response.
        self._payload_holds = None
        # SLO accounting (serve/_private/observability.py): routed-at
        # stamp for the latency histogram; recorded once, on the first
        # result()/await that settles the request
        self._t0 = time.monotonic()
        self._recorded = False

    def _record_outcome(self, error: Optional[str]) -> None:
        if self._recorded or self._handle is None:
            return
        self._recorded = True
        from ._private import observability as obs

        dep = self._handle.deployment_name
        route = getattr(self._handle, "_metric_route", "")
        if error is None:
            obs.observe_latency(dep, route, time.monotonic() - self._t0)
        elif error == "timeout":
            obs.count_timeout(dep, route)
        else:
            obs.count_error(dep, route)

    def _reroute(self) -> None:
        """Re-send this request to a live replica and adopt the new ref
        (so composition and repeat result() calls follow the retry).

        NOTE: this makes delivery at-least-once — a replica that died
        mid-execution may have run side effects before the retry. Same
        tradeoff as a load-balancing proxy; stateful non-idempotent
        deployments should disable retries by catching ActorDiedError
        upstream or keying requests idempotently.
        """
        self._handle._refresh(force=True)
        fresh = self._handle._route(self._method, self._args, self._kwargs)
        self._ref = fresh._ref

    def result(self, timeout_s: Optional[float] = None) -> Any:
        from ray_tpu.exceptions import ActorDiedError, GetTimeoutError

        from .._private import worker
        from ._private import payloads as _payloads

        for attempt in range(self._MAX_RETRIES + 1):
            try:
                # one-shot consumer get: a large (shm) response maps
                # zero-copy when local and pulls straight from the
                # owner's object agent when remote — never installed
                # into the value cache (payloads.py)
                value = worker.get_client().get(
                    [self._ref._id], timeout=timeout_s, oneshot=True
                )[0]
            except ActorDiedError:
                if self._handle is None or attempt == self._MAX_RETRIES:
                    self._record_outcome("error")
                    raise
                self._reroute()
            except GetTimeoutError:
                self._record_outcome("timeout")
                raise
            except BaseException:
                self._record_outcome("error")
                raise
            else:
                self._record_outcome(None)
                return _payloads.unwrap_result(value)

    def _to_object_ref(self):
        return self._ref

    def __await__(self):
        import asyncio

        from ray_tpu.exceptions import ActorDiedError

        from ._private import payloads as _payloads

        async def _get():
            for attempt in range(self._MAX_RETRIES + 1):
                try:
                    value = await self._ref
                except ActorDiedError:
                    if self._handle is None or attempt == self._MAX_RETRIES:
                        self._record_outcome("error")
                        raise
                    # _reroute blocks (controller RPC + replica wait):
                    # keep it off the event loop
                    await asyncio.to_thread(self._reroute)
                except BaseException:
                    self._record_outcome("error")
                    raise
                else:
                    self._record_outcome(None)
                    return _payloads.unwrap_result(value)

        return _get().__await__()


class DeploymentResponseGenerator:
    """Iterates a streaming deployment call's yielded values (parity:
    serve's DeploymentResponseGenerator over an ObjectRefGenerator)."""

    def __init__(self, ref_gen):
        self._ref_gen = ref_gen

    def __iter__(self):
        import ray_tpu

        for ref in self._ref_gen:
            yield ray_tpu.get(ref)

    async def __aiter__(self):
        import ray_tpu

        async for ref in self._ref_gen:
            yield await ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.method_name = method_name
        self._stream = False
        self._model_id = ""
        # metrics "route" tag: ingress proxies stamp their matched route
        # prefix here; direct handle calls report route=""
        self._metric_route = ""
        self._model_map: Dict[bytes, List[str]] = {}
        self._replicas: List[Any] = []
        self._outstanding: Dict[int, int] = {}
        self._inflight: Dict[Any, int] = {}  # ref -> replica id
        self._refreshed = 0.0
        self._lock = threading.Lock()

    def __reduce__(self):
        # handles travel inside deployment init args (composition);
        # router state is per-process and rebuilt on first use
        return (DeploymentHandle, (self.deployment_name, self.method_name))

    # -- API -----------------------------------------------------------
    def options(
        self,
        *,
        method_name: Optional[str] = None,
        stream: Optional[bool] = None,
        multiplexed_model_id: Optional[str] = None,
    ) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, method_name or self.method_name)
        h._replicas = self._replicas
        h._outstanding = self._outstanding
        h._refreshed = self._refreshed
        h._stream = self._stream if stream is None else stream
        h._model_id = (
            self._model_id if multiplexed_model_id is None else multiplexed_model_id
        )
        h._model_map = self._model_map
        h._metric_route = self._metric_route
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._route(self.method_name, args, kwargs)

    # -- routing -------------------------------------------------------
    def _controller(self):
        import ray_tpu

        from ._private.controller import CONTROLLER_NAME

        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._refreshed < _REFRESH_PERIOD_S and self._replicas:
                return
            self._refreshed = now
        import ray_tpu

        ctrl = self._controller()
        replicas = ray_tpu.get(ctrl.get_replicas.remote(self.deployment_name))
        model_map = (
            ray_tpu.get(ctrl.get_multiplex_map.remote(self.deployment_name))
            if self._model_id
            else {}
        )
        with self._lock:
            self._model_map = model_map
            self._replicas = replicas
            # keyed by the STABLE actor id — ActorHandle objects are
            # re-created on every refresh deserialization, so id() keys
            # would zero the load accounting each second
            self._outstanding = {
                _rid(r): self._outstanding.get(_rid(r), 0) for r in replicas
            }

    def _route(self, method: str, args, kwargs) -> DeploymentResponse:
        from ..util import tracing as _tracing

        from ._private import observability as obs

        # serve.route spans the whole router hop: replica wait + pick +
        # dispatch. Inherits the proxy's trace (ambient context) or
        # head-samples a fresh one for direct handle calls.
        tr = obs.begin_trace()
        t_route0 = time.monotonic()
        # unwrap composed responses: pass the underlying ref so the
        # downstream replica receives the resolved value (model
        # composition, reference handle.py DeploymentResponse chaining)
        args = tuple(
            a._to_object_ref() if isinstance(a, DeploymentResponse) else a
            for a in args
        )
        kwargs = {
            k: (v._to_object_ref() if isinstance(v, DeploymentResponse) else v)
            for k, v in kwargs.items()
        }
        # zero-copy data plane: oversized raw payloads (top-level args/
        # kwargs + one level into dict args, covering the ingress request
        # dict's "body") spill onto the direct object plane and travel as
        # PayloadRef markers; the replica bulk-resolves them. Streaming
        # calls skip the codec — handle_request_streaming has no resolve
        # pass.
        payload_holds: List[Any] = []
        payload_deps: List[bytes] = []
        if not self._stream:
            from ._private import payloads as _payloads

            t_spill0 = time.monotonic()
            args, kwargs, payload_holds, payload_deps, spilled_bytes = (
                _payloads.spill_args(args, kwargs)
            )
            if payload_holds and tr is not None:
                obs.emit_span(
                    "serve.payload_put", "serve.payload_put", tr[0], tr[1],
                    t_spill0, time.monotonic(),
                    deployment=self.deployment_name,
                    n=len(payload_holds), nbytes=spilled_bytes,
                )
        deadline = time.monotonic() + 30.0
        while True:
            self._refresh()
            with self._lock:
                replicas = list(self._replicas)
            if replicas:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no live replicas for deployment "
                    f"{self.deployment_name!r} after 30s"
                )
            time.sleep(0.05)
        self._reconcile_inflight()
        if self._model_id:
            # model affinity (reference pow_2_scheduler multiplex rank):
            # pick among replicas already holding the model; fall back
            # to the full set (the chosen replica then loads it)
            with self._lock:
                holders = [
                    r
                    for r in replicas
                    if self._model_id in self._model_map.get(_rid(r), ())
                ]
            if holders:
                replicas = holders
        replica = self._pick(replicas)
        rid = _rid(replica)
        if self._stream:
            # streamed responses flow as an ObjectRefGenerator; no
            # transparent replica retry (a half-consumed stream is not
            # transparently re-executable), and no _outstanding
            # accounting — there is no single completion ref to credit
            # the count back against
            ref_gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, args, kwargs, self._model_id)
            return DeploymentResponseGenerator(ref_gen)
        with self._lock:
            self._outstanding[rid] = self._outstanding.get(rid, 0) + 1
        obs.count_request(self.deployment_name, self._metric_route)
        handle_request = replica.handle_request
        if payload_deps:
            # spilled payload ids ride the dispatch's arg_deps: the hub
            # pins them while the call is in flight, so a caller dropping
            # the response (and its holds) early can't free a payload the
            # replica hasn't fetched yet
            handle_request = handle_request.options(_extra_arg_deps=payload_deps)
        if tr is None:
            ref = handle_request.remote(
                method, args, kwargs, self._model_id
            )
        else:
            # the enqueue wall stamp rides as an ordinary pickled arg;
            # the replica opens serve.queue_wait at this instant. The
            # ambient push makes the task-layer submit span (and the
            # replica's execute chain) parent under serve.route.
            route_sid = _tracing.new_span_id()
            meta = {"enq_wall": _tracing.wall_at(time.monotonic())}
            token = _tracing.push_context((tr[0], route_sid))
            try:
                ref = handle_request.remote(
                    method, args, kwargs, self._model_id, meta
                )
            finally:
                _tracing.pop_context(token)
            obs.emit_span(
                "serve.route", "serve.route", tr[0], tr[1],
                t_route0, time.monotonic(), span_id=route_sid,
                deployment=self.deployment_name, method=method,
            )
        with self._lock:
            self._inflight[ref] = rid
        resp = DeploymentResponse(ref, self, method, args, kwargs)
        if payload_holds:
            resp._payload_holds = payload_holds
        return resp

    def _pick(self, replicas: List[Any]):
        """Power-of-two-choices on caller-side outstanding counts."""
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        with self._lock:
            la = self._outstanding.get(_rid(a), 0)
            lb = self._outstanding.get(_rid(b), 0)
        return a if la <= lb else b

    def _reconcile_inflight(self) -> None:
        """Lazily credit finished requests back to their replicas (a
        zero-timeout wait on the next route, instead of a watcher thread
        per request — the reference likewise folds completion accounting
        into the router's request path)."""
        import ray_tpu

        with self._lock:
            refs = list(self._inflight.keys())
        if not refs:
            return
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        with self._lock:
            for ref in done:
                rid = self._inflight.pop(ref, None)
                if rid is not None and self._outstanding.get(rid, 0) > 0:
                    self._outstanding[rid] -= 1


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._route(self._method, args, kwargs)
