"""DeploymentHandle: the caller-side router.

Parity: python/ray/serve/handle.py + _private/router.py:321 +
replica_scheduler/pow_2_scheduler.py:52 — requests route to the replica
with the shorter queue among two random choices (power of two choices),
tracked by caller-side outstanding counts and corrected by periodic
replica-list refresh. ``.remote()`` returns a DeploymentResponse future
(composable: passing a response as an argument chains on its result).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

_REFRESH_PERIOD_S = 1.0

# serve-scope chaos engine (route_partition refresh blackhole), built
# once per routing process; None-cached when the plan is inert
_chaos_engine = None
_chaos_ready = False


def _serve_chaos():
    global _chaos_engine, _chaos_ready
    if not _chaos_ready:
        from .._private import chaos as chaos_mod

        _chaos_engine = chaos_mod.engine_for("serve")
        _chaos_ready = True
    return _chaos_engine


def _cfg():
    from .._private.config import RAY_TPU_CONFIG

    return RAY_TPU_CONFIG


def _rid(replica) -> bytes:
    """Stable identity of a replica actor across handle refreshes."""
    return replica._actor_id.binary()


class DeploymentResponse:
    """Future for one request (parity: serve.handle.DeploymentResponse).

    Holds the routing context so a request that landed on a replica torn
    down mid-flight (redeploy, scale-down, crash) is transparently
    re-routed — the reference's router likewise reschedules on replica
    death rather than surfacing ActorDiedError to the caller. The retry
    budget is bounded (``serve_retry_attempts``) with growing jittered
    backoff, and every blocking wait is capped by the request deadline.
    """

    def __init__(self, ref, handle=None, method=None, args=(), kwargs=None):
        self._ref = ref
        self._handle = handle
        self._method = method
        self._args = args
        self._kwargs = kwargs or {}
        # routed replica id (ejection accounting) + request deadline
        # (monotonic; every result()/await wait derives from it)
        self._rid: Optional[bytes] = None
        self._deadline_mono: Optional[float] = None
        # owned twin refs of payloads spilled onto the object plane for
        # this request (serve/_private/payloads.py). Living here — not
        # on the task ref — they survive _reroute's ref swap, and
        # ownership GC frees the segments when the caller drops the
        # response.
        self._payload_holds = None
        # SLO accounting (serve/_private/observability.py): routed-at
        # stamp for the latency histogram; recorded once, on the first
        # result()/await that settles the request
        self._t0 = time.monotonic()
        self._recorded = False

    def _record_outcome(self, error: Optional[str]) -> None:
        if self._recorded or self._handle is None:
            return
        self._recorded = True
        from ._private import observability as obs

        dep = self._handle.deployment_name
        route = getattr(self._handle, "_metric_route", "")
        if error is None:
            obs.observe_latency(dep, route, time.monotonic() - self._t0)
        elif error == "timeout":
            obs.count_timeout(dep, route)
        else:
            obs.count_error(dep, route)

    def _reroute(self) -> None:
        """Re-send this request to a live replica and adopt the new ref
        (so composition and repeat result() calls follow the retry).
        The original deadline rides along — a retry never extends it.

        NOTE: this makes delivery at-least-once — a replica that died
        mid-execution may have run side effects before the retry. Same
        tradeoff as a load-balancing proxy; stateful non-idempotent
        deployments should disable retries by catching ActorDiedError
        upstream or keying requests idempotently.
        """
        self._handle._refresh(force=True)
        fresh = self._handle._route(
            self._method, self._args, self._kwargs,
            _retry_deadline=self._deadline_mono,
        )
        self._ref = fresh._ref
        self._rid = fresh._rid

    def _note_failure(self) -> None:
        if self._handle is not None and self._rid is not None:
            self._handle._note_failure(self._rid)

    def _note_success(self) -> None:
        if self._handle is not None and self._rid is not None:
            self._handle._note_success(self._rid)

    def _remaining_s(self) -> Optional[float]:
        """Seconds until the request deadline; None when undeadlined.
        Raises GetTimeoutError (recorded as a timeout) once expired."""
        if self._deadline_mono is None:
            return None
        remaining = self._deadline_mono - time.monotonic()
        if remaining <= 0:
            from ray_tpu.exceptions import GetTimeoutError

            self._record_outcome("timeout")
            raise GetTimeoutError(
                f"request to deployment "
                f"{getattr(self._handle, 'deployment_name', '?')!r} "
                f"exceeded its deadline"
            )
        return remaining

    def _retry_delay(self, attempt: int) -> float:
        """Growing jittered backoff for transparent replica retries,
        capped by the remaining deadline."""
        base = float(_cfg().get("serve_retry_base_s", 0.05))
        delay = base * (2 ** attempt) * (0.5 + random.random())
        if self._deadline_mono is not None:
            delay = min(
                delay, max(0.0, self._deadline_mono - time.monotonic())
            )
        return delay

    def result(self, timeout_s: Optional[float] = None) -> Any:
        from ray_tpu.exceptions import ActorDiedError, GetTimeoutError

        from .._private import worker
        from ._private import payloads as _payloads

        budget = max(0, int(_cfg().get("serve_retry_attempts", 3)))
        attempt = 0
        while True:
            remaining = self._remaining_s()
            t = (
                remaining
                if timeout_s is None
                else (timeout_s if remaining is None else min(timeout_s, remaining))
            )
            try:
                # one-shot consumer get: a large (shm) response maps
                # zero-copy when local and pulls straight from the
                # owner's object agent when remote — never installed
                # into the value cache (payloads.py)
                value = worker.get_client().get(
                    [self._ref._id], timeout=t, oneshot=True
                )[0]
            except ActorDiedError:
                self._note_failure()
                if self._handle is None or attempt >= budget:
                    self._record_outcome("error")
                    raise
                time.sleep(self._retry_delay(attempt))
                attempt += 1
                self._reroute()
            except GetTimeoutError:
                self._record_outcome("timeout")
                raise
            except BaseException:
                self._record_outcome("error")
                raise
            else:
                self._note_success()
                self._record_outcome(None)
                return _payloads.unwrap_result(value)

    def _to_object_ref(self):
        return self._ref

    def __await__(self):
        import asyncio

        from ray_tpu.exceptions import ActorDiedError, GetTimeoutError

        from ._private import payloads as _payloads

        async def _get():
            budget = max(0, int(_cfg().get("serve_retry_attempts", 3)))
            attempt = 0
            while True:
                remaining = self._remaining_s()
                try:
                    if remaining is None:
                        value = await self._ref
                    else:

                        async def _awaited():
                            return await self._ref

                        try:
                            value = await asyncio.wait_for(
                                _awaited(), timeout=remaining
                            )
                        except asyncio.TimeoutError:
                            self._record_outcome("timeout")
                            raise GetTimeoutError(
                                "request exceeded its deadline"
                            ) from None
                except ActorDiedError:
                    self._note_failure()
                    if self._handle is None or attempt >= budget:
                        self._record_outcome("error")
                        raise
                    await asyncio.sleep(self._retry_delay(attempt))
                    attempt += 1
                    # _reroute blocks (controller RPC + replica wait):
                    # keep it off the event loop
                    await asyncio.to_thread(self._reroute)
                except BaseException:
                    self._record_outcome("error")
                    raise
                else:
                    self._note_success()
                    self._record_outcome(None)
                    return _payloads.unwrap_result(value)

        return _get().__await__()


class DeploymentResponseGenerator:
    """Iterates a streaming deployment call's yielded values (parity:
    serve's DeploymentResponseGenerator over an ObjectRefGenerator)."""

    def __init__(self, ref_gen):
        self._ref_gen = ref_gen

    def __iter__(self):
        import ray_tpu

        for ref in self._ref_gen:
            yield ray_tpu.get(ref)

    async def __aiter__(self):
        import ray_tpu

        async for ref in self._ref_gen:
            yield await ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.method_name = method_name
        self._stream = False
        self._model_id = ""
        # metrics "route" tag: ingress proxies stamp their matched route
        # prefix here; direct handle calls report route=""
        self._metric_route = ""
        self._model_map: Dict[bytes, List[str]] = {}
        self._replicas: List[Any] = []
        self._outstanding: Dict[int, int] = {}
        self._inflight: Dict[Any, int] = {}  # ref -> replica id
        self._refreshed = 0.0
        self._lock = threading.Lock()
        # admission control: deployment cap learned from the controller
        # at refresh (None until learned -> config default applies)
        self._max_queued: Optional[int] = None
        # per-request deadline override (None -> serve_request_timeout_s)
        self._request_timeout_s: Optional[float] = None
        # health ejection: consecutive-failure streaks and the ejected
        # set (rid -> replica handle, kept out of the candidate pool
        # while a background prober re-checks it with backoff)
        self._fail_streaks: Dict[bytes, int] = {}
        self._ejected: Dict[bytes, Any] = {}
        self._prober: Optional[threading.Thread] = None

    def __reduce__(self):
        # handles travel inside deployment init args (composition);
        # router state is per-process and rebuilt on first use
        return (DeploymentHandle, (self.deployment_name, self.method_name))

    # -- API -----------------------------------------------------------
    def options(
        self,
        *,
        method_name: Optional[str] = None,
        stream: Optional[bool] = None,
        multiplexed_model_id: Optional[str] = None,
        request_timeout_s: Optional[float] = None,
    ) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, method_name or self.method_name)
        h._replicas = self._replicas
        h._outstanding = self._outstanding
        # inflight refs ride along with the outstanding counts: a view
        # must be able to credit back completions another view routed,
        # or the shared queue-depth estimate only ever grows (and the
        # admission gate sheds forever)
        h._inflight = self._inflight
        h._refreshed = self._refreshed
        h._stream = self._stream if stream is None else stream
        h._model_id = (
            self._model_id if multiplexed_model_id is None else multiplexed_model_id
        )
        h._model_map = self._model_map
        h._metric_route = self._metric_route
        h._max_queued = self._max_queued
        h._request_timeout_s = (
            self._request_timeout_s
            if request_timeout_s is None
            else request_timeout_s
        )
        # ejection state is shared: an options() view routing to the
        # same deployment must not resurrect an ejected replica
        h._fail_streaks = self._fail_streaks
        h._ejected = self._ejected
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._route(self.method_name, args, kwargs)

    # -- routing -------------------------------------------------------
    def _controller(self):
        import ray_tpu

        from ._private.controller import CONTROLLER_NAME

        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._refreshed < _REFRESH_PERIOD_S and self._replicas:
                return
            self._refreshed = now
        # route_partition chaos: the refresh RPC is blackholed for the
        # window — the handle keeps routing on its stale cached set
        # (forced refreshes, e.g. a retry's, are eaten too)
        eng = _serve_chaos()
        if eng is not None and eng.route_partition_active(self.deployment_name):
            eng.record("route_partition", deployment=self.deployment_name)
            return
        import ray_tpu

        ctrl = self._controller()
        info = ray_tpu.get(ctrl.get_routing_info.remote(self.deployment_name))
        replicas = info["replicas"]
        model_map = (
            ray_tpu.get(ctrl.get_multiplex_map.remote(self.deployment_name))
            if self._model_id
            else {}
        )
        with self._lock:
            self._model_map = model_map
            self._replicas = replicas
            self._max_queued = info.get("max_queued_requests", 0)
            # keyed by the STABLE actor id — ActorHandle objects are
            # re-created on every refresh deserialization, so id() keys
            # would zero the load accounting each second
            self._outstanding = {
                _rid(r): self._outstanding.get(_rid(r), 0) for r in replicas
            }
            # a replaced replica leaves the ejected set with its rid —
            # the controller already swapped in a successor
            live = {_rid(r) for r in replicas}
            for rid in list(self._ejected):
                if rid not in live:
                    self._ejected.pop(rid, None)
                    self._fail_streaks.pop(rid, None)

    def _route(
        self, method: str, args, kwargs, _retry_deadline: Optional[float] = None
    ) -> DeploymentResponse:
        from ray_tpu.exceptions import RequestExpiredError, RequestShedError

        from ..util import tracing as _tracing

        from ._private import observability as obs

        # serve.route spans the whole router hop: replica wait + pick +
        # dispatch. Inherits the proxy's trace (ambient context) or
        # head-samples a fresh one for direct handle calls.
        tr = obs.begin_trace()
        t_route0 = time.monotonic()
        # the request deadline is born HERE (config default or
        # handle.options(request_timeout_s=...)); a transparent retry
        # passes the original in — rerouting never extends it
        if _retry_deadline is not None:
            deadline_mono: Optional[float] = _retry_deadline
        else:
            timeout_s = self._request_timeout_s
            if timeout_s is None:
                timeout_s = float(_cfg().get("serve_request_timeout_s", 60.0))
            deadline_mono = (
                t_route0 + timeout_s if timeout_s and timeout_s > 0 else None
            )
        # admission control: outstanding (routed, unsettled) requests
        # vs the deployment cap — past it, shed NOW, before any payload
        # spill or replica wait. Retries skip the gate: their request
        # was already admitted once. Shed accounting is disjoint from
        # everything downstream (a shed request is never counted
        # routed, drained, dropped, or expired).
        self._refresh()
        if _retry_deadline is None:
            cap = self._max_queued
            if not cap:
                cap = int(_cfg().get("serve_max_queued_requests", 0))
            if cap and cap > 0:
                self._reconcile_inflight()
                with self._lock:
                    queued = sum(self._outstanding.values())
                if queued >= cap:
                    obs.count_shed(self.deployment_name, self._metric_route)
                    raise RequestShedError(self.deployment_name, queued, cap)
        # unwrap composed responses: pass the underlying ref so the
        # downstream replica receives the resolved value (model
        # composition, reference handle.py DeploymentResponse chaining)
        args = tuple(
            a._to_object_ref() if isinstance(a, DeploymentResponse) else a
            for a in args
        )
        kwargs = {
            k: (v._to_object_ref() if isinstance(v, DeploymentResponse) else v)
            for k, v in kwargs.items()
        }
        # zero-copy data plane: oversized raw payloads (top-level args/
        # kwargs + one level into dict args, covering the ingress request
        # dict's "body") spill onto the direct object plane and travel as
        # PayloadRef markers; the replica bulk-resolves them. Streaming
        # calls skip the codec — handle_request_streaming has no resolve
        # pass.
        payload_holds: List[Any] = []
        payload_deps: List[bytes] = []
        if not self._stream:
            from ._private import payloads as _payloads

            t_spill0 = time.monotonic()
            args, kwargs, payload_holds, payload_deps, spilled_bytes = (
                _payloads.spill_args(args, kwargs)
            )
            if payload_holds and tr is not None:
                obs.emit_span(
                    "serve.payload_put", "serve.payload_put", tr[0], tr[1],
                    t_spill0, time.monotonic(),
                    deployment=self.deployment_name,
                    n=len(payload_holds), nbytes=spilled_bytes,
                )
        # replica wait bounded by the request deadline (was a literal
        # 30 s): an expired request fails fast instead of parking
        wait_deadline = (
            deadline_mono
            if deadline_mono is not None
            else t_route0 + float(_cfg().get("serve_request_timeout_s", 60.0))
        )
        delay = 0.02
        while True:
            self._refresh()
            with self._lock:
                replicas = [
                    r for r in self._replicas if _rid(r) not in self._ejected
                ]
                if not replicas and self._replicas:
                    # every replica ejected: fail open on the full set
                    # rather than refusing all traffic on a router-local
                    # health guess
                    replicas = list(self._replicas)
            if replicas:
                break
            if time.monotonic() > wait_deadline:
                obs.count_expired(self.deployment_name, self._metric_route)
                raise RequestExpiredError(
                    self.deployment_name,
                    f"no live replicas for deployment "
                    f"{self.deployment_name!r} within the request deadline",
                )
            time.sleep(delay)
            delay = min(0.25, delay * 1.5)
        self._reconcile_inflight()
        if self._model_id:
            # model affinity (reference pow_2_scheduler multiplex rank):
            # pick among replicas already holding the model; fall back
            # to the full set (the chosen replica then loads it)
            with self._lock:
                holders = [
                    r
                    for r in replicas
                    if self._model_id in self._model_map.get(_rid(r), ())
                ]
            if holders:
                replicas = holders
        replica = self._pick(replicas)
        rid = _rid(replica)
        if self._stream:
            # streamed responses flow as an ObjectRefGenerator; no
            # transparent replica retry (a half-consumed stream is not
            # transparently re-executable), and no _outstanding
            # accounting — there is no single completion ref to credit
            # the count back against
            ref_gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, args, kwargs, self._model_id)
            return DeploymentResponseGenerator(ref_gen)
        with self._lock:
            self._outstanding[rid] = self._outstanding.get(rid, 0) + 1
        obs.count_request(self.deployment_name, self._metric_route)
        handle_request = replica.handle_request
        if payload_deps:
            # spilled payload ids ride the dispatch's arg_deps: the hub
            # pins them while the call is in flight, so a caller dropping
            # the response (and its holds) early can't free a payload the
            # replica hasn't fetched yet
            handle_request = handle_request.options(_extra_arg_deps=payload_deps)
        # request_meta always rides now: the deadline propagates to the
        # replica (pre-execute expiry check) and its batch queue; the
        # enqueue wall stamp is added only when traced
        meta: Optional[Dict[str, Any]] = None
        if deadline_mono is not None:
            meta = {"deadline_wall": _tracing.wall_at(deadline_mono)}
        if tr is None:
            ref = handle_request.remote(
                method, args, kwargs, self._model_id, meta
            )
        else:
            # the enqueue wall stamp rides as an ordinary pickled arg;
            # the replica opens serve.queue_wait at this instant. The
            # ambient push makes the task-layer submit span (and the
            # replica's execute chain) parent under serve.route.
            route_sid = _tracing.new_span_id()
            meta = dict(meta or {})
            meta["enq_wall"] = _tracing.wall_at(time.monotonic())
            token = _tracing.push_context((tr[0], route_sid))
            try:
                ref = handle_request.remote(
                    method, args, kwargs, self._model_id, meta
                )
            finally:
                _tracing.pop_context(token)
            obs.emit_span(
                "serve.route", "serve.route", tr[0], tr[1],
                t_route0, time.monotonic(), span_id=route_sid,
                deployment=self.deployment_name, method=method,
            )
        with self._lock:
            self._inflight[ref] = rid
        resp = DeploymentResponse(ref, self, method, args, kwargs)
        resp._rid = rid
        resp._deadline_mono = deadline_mono
        if payload_holds:
            resp._payload_holds = payload_holds
        return resp

    def _pick(self, replicas: List[Any]):
        """Power-of-two-choices on caller-side outstanding counts."""
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        with self._lock:
            la = self._outstanding.get(_rid(a), 0)
            lb = self._outstanding.get(_rid(b), 0)
        return a if la <= lb else b

    def _reconcile_inflight(self) -> None:
        """Lazily credit finished requests back to their replicas (a
        zero-timeout wait on the next route, instead of a watcher thread
        per request — the reference likewise folds completion accounting
        into the router's request path)."""
        import ray_tpu

        with self._lock:
            refs = list(self._inflight.keys())
        if not refs:
            return
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        with self._lock:
            for ref in done:
                rid = self._inflight.pop(ref, None)
                if rid is not None and self._outstanding.get(rid, 0) > 0:
                    self._outstanding[rid] -= 1

    # -- health ejection ----------------------------------------------
    def _note_failure(self, rid: bytes) -> None:
        """One failed/timed-out request on a replica. At
        ``serve_ejection_failures`` consecutive failures the replica
        leaves the candidate set and a background prober re-checks it
        with jittered exponential backoff until healthy (or dead)."""
        threshold = int(_cfg().get("serve_ejection_failures", 3))
        if threshold <= 0:
            return
        with self._lock:
            streak = self._fail_streaks.get(rid, 0) + 1
            self._fail_streaks[rid] = streak
            if streak < threshold or rid in self._ejected:
                return
            replica = next(
                (r for r in self._replicas if _rid(r) == rid), None
            )
            if replica is None:
                self._fail_streaks.pop(rid, None)
                return
            self._ejected[rid] = replica
        from ._private import observability as obs

        obs.count_ejection(self.deployment_name)
        self._ensure_prober()

    def _note_success(self, rid: bytes) -> None:
        with self._lock:
            self._fail_streaks.pop(rid, None)

    def _ensure_prober(self) -> None:
        with self._lock:
            if self._prober is not None and self._prober.is_alive():
                return
            self._prober = threading.Thread(
                target=self._probe_ejected,
                daemon=True,
                name=f"serve-probe-{self.deployment_name}",
            )
            self._prober.start()

    def _probe_ejected(self) -> None:
        """Re-probe ejected replicas until each recovers (restored to
        the candidate set) or turns out dead (left out for good — the
        controller replaces it). Exits when the ejected set drains."""
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError

        base = float(_cfg().get("serve_probe_base_s", 0.25))
        cap = float(_cfg().get("serve_probe_max_s", 5.0))
        delay = base
        while True:
            with self._lock:
                targets = dict(self._ejected)
            if not targets:
                return
            time.sleep(delay * (0.5 + random.random()))
            delay = min(cap, delay * 2.0)
            for rid, replica in targets.items():
                try:
                    # probes are deliberately sequential: each replica
                    # gets its own verdict + bounded timeout
                    ray_tpu.get(replica.check_health.remote(), timeout=2.0)  # graftlint: disable=GL004,GL017 — sequential health probe with a fixed per-replica budget
                except ActorDiedError:
                    # really dead: stop probing; the reconcile loop
                    # replaces it and _refresh prunes the rid
                    with self._lock:
                        self._ejected.pop(rid, None)
                        self._fail_streaks.pop(rid, None)
                except Exception:
                    continue  # still unhealthy: keep backing off
                else:
                    with self._lock:
                        self._ejected.pop(rid, None)
                        self._fail_streaks.pop(rid, None)
                    delay = base


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._route(self._method, args, kwargs)
