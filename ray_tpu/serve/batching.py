"""@serve.batch — transparent request batching.

Parity: python/ray/serve/batching.py — an async method decorated with
@serve.batch collects concurrent calls into a list and invokes the
underlying function once per batch (max_batch_size or
batch_wait_timeout_s, whichever first). On TPU replicas this is the
lever that turns scalar requests into MXU-sized batches.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import time
import weakref
from typing import Any, Callable, List, Optional

# every live batch queue in this replica process — Replica.stats() sums
# their depths into the "queued" load signal the controller scrapes
_QUEUES: "weakref.WeakSet[_BatchQueue]" = weakref.WeakSet()

# the ambient request deadline (monotonic, THIS process's clock), set by
# Replica.handle_request before the user callable runs. A @serve.batch
# wrapper reads it at submit so the batch loop can drop a member whose
# deadline expires while parked — before the batch executes — without
# poisoning the rest of the batch.
_deadline_ctx: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "serve_request_deadline", default=None
)


def queued_total() -> int:
    """Requests parked in this process's batch queues right now."""
    total = 0
    for q in list(_QUEUES):
        if q.queue is not None:
            total += q.queue.qsize()
    return total


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.queue: Optional[asyncio.Queue] = None
        self.task: Optional[asyncio.Task] = None
        _QUEUES.add(self)

    def _ensure(self):
        if self.queue is None:
            self.queue = asyncio.Queue()
            self.task = asyncio.get_running_loop().create_task(self._loop())

    async def submit(self, item) -> Any:
        from ..util import tracing as _tracing

        self._ensure()
        fut = asyncio.get_running_loop().create_future()
        # carry the submitter's trace context AND deadline into the
        # batch loop: the loop task was created from whichever request
        # arrived first and its ambient context is useless for later
        # members
        await self.queue.put(
            (item, fut, _tracing.current_context(), time.monotonic(),
             _deadline_ctx.get())
        )
        return await fut

    async def _loop(self):
        from ._private import observability as obs
        from ._private import payloads as _payloads

        while True:
            entry = await self.queue.get()
            batch = [entry]
            deadline = asyncio.get_running_loop().time() + self.timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(self.queue.get(), timeout=remaining)
                    )
                except asyncio.TimeoutError:
                    break
            # pre-execute deadline check: a member that expired while
            # parked is dropped HERE — its future gets the expiry error
            # and it never reaches the user function, so an abandoned
            # request can't poison (or bloat) the batch it parked in
            t_exec = time.monotonic()
            deployment = obs.current_deployment()
            expired = [b for b in batch
                       if b[4] is not None and b[4] <= t_exec]
            if expired:
                from ray_tpu.exceptions import RequestExpiredError

                for _, fut, _, _, _ in expired:
                    if not fut.done():
                        fut.set_exception(RequestExpiredError(deployment))
                    obs.count_expired(deployment)
                batch = [b for b in batch if b[4] is None or b[4] > t_exec]
                if not batch:
                    continue
            items = [b[0] for b in batch]
            futs = [b[1] for b in batch]
            obs.observe_batch(deployment, len(batch), self.max_batch_size)
            for _, _, ctx, t_enq, _ in batch:
                # one serve.batch_wait per traced member: parked from its
                # submit until the batch fired, nested under that
                # request's serve.execute span
                if ctx is not None:
                    obs.emit_span(
                        "serve.batch_wait", "serve.batch_wait",
                        ctx[0], ctx[1], t_enq, t_exec,
                        deployment=deployment,
                        batch_size=len(batch),
                        max_batch_size=self.max_batch_size,
                    )
            if _payloads.has_payload_refs(items):
                # zero-copy payload plane: ALL members' spilled bodies
                # resolve through ONE shared bulk get — the reason
                # replica.handle_request defers resolution for batch
                # targets. Off the event loop: the fetch may block on a
                # remote agent and must not park unrelated queues.
                # (After the batch_wait spans: their window ends at
                # t_exec, so the fetch slice stays payload_fetch's.)
                t_fetch0 = time.monotonic()
                items, n_fetched, fetched_bytes = (
                    await asyncio.get_running_loop().run_in_executor(
                        None, _payloads.resolve_batch_items, items
                    )
                )
                t_fetch1 = time.monotonic()
                for _, _, ctx, _, _ in batch:
                    # charged per traced member: the batch shares the
                    # wall-clock window, not N copies of the bytes
                    if ctx is not None:
                        obs.emit_span(
                            "serve.payload_fetch", "serve.payload_fetch",
                            ctx[0], ctx[1], t_fetch0, t_fetch1,
                            deployment=deployment, n=n_fetched,
                            nbytes=fetched_bytes, shared=len(batch),
                        )
            try:
                results = await self.fn(items)
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} results "
                        f"for a batch of {len(items)}"
                    )
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 10,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorator: async def method(self, items: List[T]) -> List[R]
    becomes callable with a single item."""

    def wrap(fn):
        attr = f"__batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(self_or_item, *args):
            # methods: first arg is self; functions: first arg is the item
            if args:
                self, item = self_or_item, args[0]
                bound = functools.partial(fn, self)
                holder = self
            else:
                item = self_or_item
                bound = fn
                holder = wrapper
            q = getattr(holder, attr, None)
            if q is None:
                q = _BatchQueue(bound, max_batch_size, batch_wait_timeout_s)
                setattr(holder, attr, q)
            return await q.submit(item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
