"""Model multiplexing: many models per deployment, LRU-cached per replica.

Parity: python/ray/serve/multiplex.py (`_ModelMultiplexWrapper`) +
api.py ``@serve.multiplexed`` / ``serve.get_multiplexed_model_id``.
The reference's replicas push their loaded-model-id set to the
controller, and routers prefer replicas already holding the requested
model. Here the model-id set rides the controller's existing batched
health-check ping (``Replica.stats``), and the handle's power-of-two
router restricts its candidate set to model-holding replicas when
``handle.options(multiplexed_model_id=...)`` is used.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import inspect
import threading
from collections import OrderedDict
from typing import Any, Callable, List, Optional

_model_id_ctx: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)

# All wrappers live in the replica's worker process; the replica reports
# the union of their loaded ids through stats().
_registry_lock = threading.Lock()
_wrappers: List["_ModelMultiplexWrapper"] = []


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id the caller routed with."""
    return _model_id_ctx.get()


def registered_model_ids() -> List[str]:
    with _registry_lock:
        wrappers = list(_wrappers)
    ids: List[str] = []
    for w in wrappers:
        ids.extend(w.model_ids())
    return ids


class _FnReporter:
    def __init__(self, fn):
        self._fn = fn

    def model_ids(self) -> List[str]:
        return list(self._fn())


def register_model_reporter(fn) -> Any:
    """Public hook for components with their own model caches (e.g. the
    LLM server's LoRA engines): ``fn() -> list[str]`` of loaded ids.
    Returns a handle for unregister_model_reporter."""
    reporter = _FnReporter(fn)
    with _registry_lock:
        _wrappers.append(reporter)
    return reporter


def unregister_model_reporter(handle) -> None:
    with _registry_lock:
        try:
            _wrappers.remove(handle)
        except ValueError:
            pass


class _ModelMultiplexWrapper:
    """Per-replica LRU of loaded models keyed by model id."""

    def __init__(self, load_fn: Callable, max_num_models: int):
        self._load_fn = load_fn
        self._max = max_num_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        with _registry_lock:
            _wrappers.append(self)

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._models)

    def load_model(self, owner, model_id: str) -> Any:
        if not model_id:
            raise ValueError(
                "multiplexed call without a model id — route with "
                "handle.options(multiplexed_model_id=...)"
            )
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        # Load outside the lock (loads can be slow); last-write-wins on
        # a racing duplicate load of the same id.
        import time

        from ..util import tracing as _tracing
        from ._private import observability as obs

        ctx = _tracing.current_context()
        t0 = time.monotonic()
        model = self._load_fn(owner, model_id)
        if inspect.iscoroutine(model):
            model = _run_sync(model)
        # an LRU miss is the multiplexing cost: surface it as a span on
        # the traced request that paid it, and as a swap counter
        obs.count_model_swap(obs.current_deployment())
        if ctx is not None:
            obs.emit_span(
                "serve.multiplex_swap", "serve.multiplex_swap",
                ctx[0], ctx[1], t0, time.monotonic(),
                deployment=obs.current_deployment(), model_id=model_id,
            )
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self._max:
                evicted_id, evicted = self._models.popitem(last=False)
                del evicted  # drop our ref; __del__ frees TPU buffers
        return model


def _run_sync(coro):
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    # Called from inside an async replica: the caller should have
    # awaited; run in a fresh loop on a helper thread.
    out: dict = {}

    def _runner():
        out["v"] = asyncio.run(coro)

    t = threading.Thread(target=_runner)
    t.start()
    t.join()
    return out["v"]


def multiplexed(
    func: Optional[Callable] = None, *, max_num_models_per_replica: int = 3
):
    """Decorator for the model-loading method of a deployment.

    class Translator:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            return load(model_id)

        def __call__(self, text):
            model = self.get_model(serve.get_multiplexed_model_id())
            ...
    """

    def _wrap(fn: Callable):
        wrapper_holder: dict = {}

        @functools.wraps(fn)
        def wrapped(self, model_id: Optional[str] = None):
            mux = wrapper_holder.get("w")
            if mux is None:
                mux = _ModelMultiplexWrapper(fn, max_num_models_per_replica)
                wrapper_holder["w"] = mux
            return mux.load_model(self, model_id or get_multiplexed_model_id())

        wrapped.__serve_multiplexed__ = True
        return wrapped

    if func is not None:
        return _wrap(func)
    return _wrap
