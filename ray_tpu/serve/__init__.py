"""ray_tpu.serve — model serving.

Parity: python/ray/serve/ (api.py:591 serve.run; @serve.deployment;
DeploymentHandle composition; @serve.batch; controller/replica/proxy
architecture §3.6). TPU angle: replicas with ``num_tpus`` pin chips for
their lifetime so jitted models stay compiled+resident, and
@serve.batch feeds them MXU-sized batches.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from .batching import batch
from .handle import DeploymentHandle, DeploymentResponse
from .multiplex import get_multiplexed_model_id, multiplexed
from .response import Response
from ._private.controller import CONTROLLER_NAME, DeploymentInfo, ServeController

__all__ = [
    "Application",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "Response",
    "batch",
    "delete",
    "deployment",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "multiplexed",
    "grpc_port",
    "run",
    "shutdown",
    "start",
    "status",
]


@dataclass
class Application:
    """A bound deployment graph node (parity: serve.Application from
    Deployment.bind)."""

    deployment: "Deployment"
    args: tuple
    kwargs: dict


class Deployment:
    def __init__(
        self,
        func_or_class: Any,
        name: str,
        *,
        num_replicas: Union[int, str, None] = None,
        max_ongoing_requests: int = 16,
        max_queued_requests: int = 0,
        ray_actor_options: Optional[Dict[str, Any]] = None,
        user_config: Any = None,
        autoscaling_config: Optional[Dict[str, Any]] = None,
    ):
        self.func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.max_ongoing_requests = max_ongoing_requests
        self.max_queued_requests = max_queued_requests
        self.ray_actor_options = ray_actor_options or {}
        self.user_config = user_config
        self.autoscaling_config = autoscaling_config

    def options(self, **opts) -> "Deployment":
        merged = {
            "num_replicas": self.num_replicas,
            "max_ongoing_requests": self.max_ongoing_requests,
            "max_queued_requests": self.max_queued_requests,
            "ray_actor_options": self.ray_actor_options,
            "user_config": self.user_config,
            "autoscaling_config": self.autoscaling_config,
        }
        name = opts.pop("name", self.name)
        merged.update(opts)
        return Deployment(self.func_or_class, name, **merged)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __call__(self, *a, **k):
        raise RuntimeError(
            f"Deployment {self.name!r} cannot be called directly; "
            "use .bind() + serve.run, then handle.remote()"
        )


def deployment(
    _func_or_class: Optional[Any] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Union[int, str, None] = None,
    max_ongoing_requests: int = 16,
    max_queued_requests: int = 0,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    user_config: Any = None,
    autoscaling_config: Optional[Dict[str, Any]] = None,
):
    """@serve.deployment decorator (reference: serve/api.py).
    ``max_queued_requests`` is the admission-control cap: outstanding
    routed requests past it are shed with a retriable error (HTTP 503)
    instead of queueing into a timeout; 0 defers to the
    ``serve_max_queued_requests`` config knob (default unlimited)."""

    def wrap(target):
        return Deployment(
            target,
            name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            ray_actor_options=ray_actor_options,
            user_config=user_config,
            autoscaling_config=autoscaling_config,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


# ---------------------------------------------------------------- control


def _get_or_start_controller():
    import ray_tpu

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        ctrl_cls = ray_tpu.remote(ServeController)
        try:
            return ctrl_cls.options(
                name=CONTROLLER_NAME, lifetime="detached", max_concurrency=16,
                num_cpus=0.1,
            ).remote()
        except Exception:
            return ray_tpu.get_actor(CONTROLLER_NAME)


_proxy = None
_grpc_proxy = None
_grpc_port = None


def start(*, http_options: Optional[Dict[str, Any]] = None, proxy: bool = False,
          grpc_options: Optional[Dict[str, Any]] = None):
    """Start serve system actors (reference: serve.start). The HTTP
    proxy starts on demand (serve.run(..., route_prefix=...) or
    proxy=True); pass grpc_options={"port": N} for the gRPC ingress
    (reference: serve.start(grpc_options=gRPCOptions(...)))."""
    global _proxy, _grpc_proxy
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    controller = _get_or_start_controller()
    if proxy and _proxy is None:
        opts = http_options or {}
        proxy_cls = ray_tpu.remote(
            __import__(
                "ray_tpu.serve._private.proxy", fromlist=["HTTPProxy"]
            ).HTTPProxy
        )
        _proxy = proxy_cls.options(max_concurrency=64, num_cpus=0.1).remote(
            opts.get("host", "127.0.0.1"), opts.get("port", 8000)
        )
        ray_tpu.get(_proxy.ping.remote())
    if grpc_options is not None and _grpc_proxy is None:
        grpc_cls = ray_tpu.remote(
            __import__(
                "ray_tpu.serve._private.proxy", fromlist=["GrpcIngress"]
            ).GrpcIngress
        )
        _grpc_proxy = grpc_cls.options(
            max_concurrency=64, num_cpus=0.1
        ).remote(
            grpc_options.get("host", "127.0.0.1"),
            grpc_options.get("port", 9000),
        )
        # ping returns the BOUND port (0 = ephemeral pick)
        global _grpc_port
        _grpc_port = ray_tpu.get(_grpc_proxy.ping.remote())
    return controller


def grpc_port() -> int:
    """The gRPC ingress's bound port (after serve.start(grpc_options=...));
    raises if the ingress is not running."""
    if _grpc_port is None:
        raise RuntimeError(
            "gRPC ingress is not running; pass grpc_options to serve.start"
        )
    return _grpc_port


def _collect_deployments(app: Application, out: Dict[str, DeploymentInfo], route_prefix):
    """DFS the bound graph: child Applications in args become
    DeploymentHandles (model composition)."""

    def convert(v):
        if isinstance(v, Application):
            _collect_deployments(v, out, None)
            return DeploymentHandle(v.deployment.name)
        return v

    args = tuple(convert(a) for a in app.args)
    kwargs = {k: convert(v) for k, v in app.kwargs.items()}
    d = app.deployment
    num = d.num_replicas
    if num in (None, "auto"):
        num = (d.autoscaling_config or {}).get("min_replicas", 1)
    out[d.name] = DeploymentInfo(
        name=d.name,
        cls=d.func_or_class,
        init_args=args,
        init_kwargs=kwargs,
        num_replicas=int(num),
        max_ongoing_requests=d.max_ongoing_requests,
        max_queued_requests=d.max_queued_requests,
        ray_actor_options=d.ray_actor_options,
        user_config=d.user_config,
        autoscaling_config=d.autoscaling_config,
        route_prefix=route_prefix,
    )


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = None,
    blocking: bool = False,
    local_testing_mode: bool = False,
    _http: bool = False,
    http_options: Optional[Dict[str, Any]] = None,
) -> DeploymentHandle:
    """Deploy an application; returns the ingress deployment's handle
    (reference: serve.run, api.py:591). ``local_testing_mode=True``
    runs the whole app in-process with no cluster (reference:
    serve/_private/local_testing_mode.py)."""
    import ray_tpu

    if local_testing_mode:
        from ._private.local_testing_mode import run_local

        return run_local(app)  # type: ignore[return-value]

    controller = start(proxy=_http or route_prefix is not None, http_options=http_options)
    infos: Dict[str, DeploymentInfo] = {}
    _collect_deployments(app, infos, route_prefix)
    # submit every deploy before blocking (controller tasks execute in
    # submission order); unlike the old one-at-a-time loop, a failing
    # deploy no longer stops later ones from being submitted, so on
    # failure tear the whole app down rather than leave it half-live
    try:
        ray_tpu.get([controller.deploy.remote(info) for info in infos.values()])
    except Exception:
        down = [controller.delete_deployment.remote(n) for n in infos]
        try:
            ray_tpu.get(down)
        except Exception:
            pass
        raise
    # wait until every deployment has live replicas; poll cadence backs
    # off gently so a slow first deploy doesn't hammer the controller
    deadline = time.monotonic() + 60
    delay = 0.05
    while time.monotonic() < deadline:
        if ray_tpu.get(controller.ready.remote()):  # graftlint: disable=GL004 — readiness poll
            break
        time.sleep(delay)
        delay = min(0.5, delay * 1.5)
    handle = DeploymentHandle(app.deployment.name)
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return handle


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name)


def status() -> Dict[str, Any]:
    import ray_tpu

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return {"applications": {}}
    return {"applications": ray_tpu.get(controller.list_deployments.remote())}


def delete(name: str) -> None:
    import ray_tpu

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete_deployment.remote(name))


def shutdown() -> None:
    global _proxy, _grpc_proxy, _grpc_port
    import ray_tpu

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote())
        ray_tpu.kill(controller)
    except Exception:
        pass
    if _proxy is not None:
        try:
            ray_tpu.kill(_proxy)
        except Exception:
            pass
        _proxy = None
    if _grpc_proxy is not None:
        try:
            ray_tpu.get(_grpc_proxy.stop.remote(), timeout=5)  # graftlint: disable=GL017 — bounded shutdown drain, requests already rejected
        except Exception:
            pass
        try:
            ray_tpu.kill(_grpc_proxy)
        except Exception:
            pass
        _grpc_proxy = None
        _grpc_port = None

from ray_tpu._private import usage as _usage

_usage.record_library_usage("serve")
