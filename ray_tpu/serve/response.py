"""Explicit HTTP responses from deployments.

Parity: returning a starlette ``Response`` from a Serve deployment
(reference: serve/_private/http_util.py Response handling) — full
control over status, content type, and headers instead of the proxy's
default coercion (bytes → octet-stream, str → text, other → JSON).
Picklable (it crosses the replica→proxy boundary as a task result).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Union


class Response:
    def __init__(
        self,
        body: Union[bytes, bytearray, str, Any] = b"",
        *,
        status: int = 200,
        content_type: Optional[str] = None,
        headers: Optional[Dict[str, str]] = None,
    ):
        self.body = body
        self.status = int(status)
        if content_type is None:
            if isinstance(body, (bytes, bytearray, memoryview)):
                content_type = "application/octet-stream"
            elif isinstance(body, str):
                content_type = "text/plain"
            else:
                content_type = "application/json"
        self.content_type = content_type
        self.headers = dict(headers or {})

    def body_bytes(self) -> bytes:
        # memoryview bodies come from the zero-copy payload plane
        # (serve/_private/payloads.py): large bodies arrive as views
        # over the mapped response segment
        if isinstance(self.body, (bytes, bytearray, memoryview)):
            return bytes(self.body)
        if isinstance(self.body, str):
            return self.body.encode()
        return json.dumps(self.body).encode()
