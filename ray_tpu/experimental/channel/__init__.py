"""Channels: fixed-shape zero-copy pipes between processes.

Parity: python/ray/experimental/channel/ — the reference backs compiled
graphs with mutable plasma objects (shared_memory_channel.py:151) and
NCCL buffers (torch_tensor_nccl_channel.py). Here:

- ``ShmChannel``: a single-producer single-consumer ring over
  multiprocessing.shared_memory for fixed-dtype/shape numpy payloads —
  the host analogue of the reference's mutable plasma channel; writes
  and reads are memcpy into mapped memory, no serialization, no
  control-plane round trip.
- The device analogue of NCCL channels on TPU is NOT a runtime object:
  stage→stage HBM movement compiles into the program itself
  (`lax.ppermute` in ray_tpu.parallel.pipeline). A cross-program HBM
  channel would force a host round-trip, so the framework keeps
  inter-stage transfer inside jit where ICI DMA is free of the host.
"""

from .shm_channel import ShmChannel

__all__ = ["ShmChannel"]
