"""Shared-memory ring channel (SPSC, fixed-shape numpy payloads).

Parity target: the reference's mutable-plasma channel
(python/ray/experimental/channel/shared_memory_channel.py:151 +
src/ray/core_worker/experimental_mutable_object_manager.h): a
pre-allocated buffer written in place per execution instead of
allocating/sealing a new object. Implementation: a ring of K slots in
one multiprocessing.shared_memory segment, with per-slot sequence
numbers for lock-free SPSC handoff (write seq = read seq + 1 protocol).

Two backends behind one API, chosen at create time and pinned in the
pickled descriptor: the C++ ring from ray_tpu/_native/ring_channel.cpp
(default when the toolchain is available — real atomics, GIL-released
microsecond waits, like the reference's C++ mutable-object channel) and
this file's pure-numpy ring (fallback; 500us polling floor).

Use between pinned actors (compiled-graph stages, data feeders):
  ch = ShmChannel.create(shape=(8, 1024), dtype="float32")
  # producer:  ch.write(arr)         (blocks when ring full)
  # consumer:  out = ch.read()       (blocks until next item)
Both ends attach from the serialized descriptor (picklable).
"""

from __future__ import annotations

import time
from multiprocessing import shared_memory
from typing import Optional, Tuple

import numpy as np

_HDR_DTYPE = np.int64
_HDR_SLOTS = 2  # [write_seq, read_seq]


class ShmChannel:
    def __init__(
        self,
        name: str,
        shape: Tuple[int, ...],
        dtype: str,
        capacity: int,
        _create: bool = False,
        backend: Optional[str] = None,
    ):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.capacity = capacity
        item_bytes = int(np.prod(self.shape)) * self.dtype.itemsize
        # Backend is fixed at create time and travels in the pickled
        # descriptor: both endpoints must agree on the segment layout.
        # "native" = the C++ ring (_native/ring_channel.cpp): real
        # acquire/release atomics + GIL-released microsecond waits;
        # "py" = this file's numpy ring.
        if backend is None:
            from ray_tpu._native import ring_native

            backend = "native" if ring_native() is not None else "py"
        self.backend = backend
        if backend == "native":
            from ray_tpu._native import ring_native

            mod = ring_native()
            if mod is None:
                raise RuntimeError(
                    "channel was created with the native backend but this "
                    "process could not build/load _ring_native"
                )
            self._mod = mod
            self._item_bytes = item_bytes
            if _create:
                self._ring = mod.create("/" + name, item_bytes, capacity)
            else:
                self._ring = mod.attach("/" + name)
            self._shm = None
            return
        hdr_bytes = _HDR_SLOTS * np.dtype(_HDR_DTYPE).itemsize
        seq_bytes = capacity * np.dtype(_HDR_DTYPE).itemsize
        total = hdr_bytes + seq_bytes + capacity * item_bytes
        if _create:
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=total
            )
            self._shm.buf[:total] = b"\x00" * total
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            # Python 3.12's resource_tracker would unlink the segment
            # when ANY attaching process exits, killing the channel for
            # every other endpoint (no track=False until 3.13) — only
            # the creator owns cleanup
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:
                pass
        buf = self._shm.buf
        self._hdr = np.frombuffer(buf, _HDR_DTYPE, count=_HDR_SLOTS)
        self._slot_seq = np.frombuffer(
            buf, _HDR_DTYPE, count=capacity, offset=hdr_bytes
        )
        self._data = np.frombuffer(
            buf,
            self.dtype,
            count=capacity * int(np.prod(self.shape)),
            offset=hdr_bytes + seq_bytes,
        ).reshape(capacity, *self.shape)

    # -- lifecycle -----------------------------------------------------
    @classmethod
    def create(
        cls,
        shape: Tuple[int, ...],
        dtype: str = "float32",
        capacity: int = 2,
        backend: Optional[str] = None,
    ) -> "ShmChannel":
        import uuid

        name = f"rt_ch_{uuid.uuid4().hex[:12]}"
        return cls(name, shape, dtype, capacity, _create=True, backend=backend)

    def __reduce__(self):
        return (
            ShmChannel,
            (self.name, self.shape, str(self.dtype), self.capacity, False,
             self.backend),
        )

    def close(self, unlink: bool = False) -> None:
        if self.backend == "native":
            self._ring = None  # capsule destructor munmaps
            if unlink:
                try:
                    self._mod.unlink("/" + self.name)
                except OSError:
                    pass
            return
        # release numpy views before closing the mapping
        self._hdr = None
        self._slot_seq = None
        self._data = None
        try:
            self._shm.close()
        except BufferError:
            pass  # a view still exported somewhere; mapping dies with us
        if unlink:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass

    def __del__(self):
        # drop numpy views BEFORE SharedMemory.__del__ tries to unmap,
        # otherwise interpreter shutdown in attached processes raises
        # BufferError("cannot close exported pointers exist")
        try:
            self.close()
        except Exception:
            pass

    # -- SPSC protocol -------------------------------------------------
    def write(self, arr: np.ndarray, timeout_s: float = 30.0) -> None:
        """Copy arr into the next slot; blocks while the ring is full."""
        arr = np.ascontiguousarray(arr, dtype=self.dtype)
        if arr.shape != self.shape:
            raise ValueError(f"channel expects shape {self.shape}, got {arr.shape}")
        if self.backend == "native":
            self._mod.write(self._ring, arr.data, float(timeout_s))
            return
        deadline = time.monotonic() + timeout_s
        w = int(self._hdr[0])
        while w - int(self._hdr[1]) >= self.capacity:  # ring full
            if time.monotonic() > deadline:
                raise TimeoutError("channel full: reader not draining")
            time.sleep(0.0005)
        slot = w % self.capacity
        self._data[slot] = arr
        self._slot_seq[slot] = w + 1  # publish AFTER the payload write
        self._hdr[0] = w + 1

    def read(self, timeout_s: float = 30.0) -> np.ndarray:
        """Copy the next item out; blocks until the writer publishes."""
        if self.backend == "native":
            out = np.empty(self.shape, self.dtype)
            self._mod.read_into(self._ring, out.data, float(timeout_s))
            return out
        deadline = time.monotonic() + timeout_s
        r = int(self._hdr[1])
        slot = r % self.capacity
        while int(self._slot_seq[slot]) != r + 1:
            if time.monotonic() > deadline:
                raise TimeoutError("channel empty: writer not producing")
            time.sleep(0.0005)
        out = np.array(self._data[slot], copy=True)
        self._hdr[1] = r + 1
        return out

    def try_read(self) -> Optional[np.ndarray]:
        if self.backend == "native":
            out = np.empty(self.shape, self.dtype)
            if self._mod.try_read_into(self._ring, out.data):
                return out
            return None
        r = int(self._hdr[1])
        if int(self._slot_seq[r % self.capacity]) != r + 1:
            return None
        return self.read(timeout_s=0.001)
