"""Distributed progress bars.

Parity: python/ray/experimental/tqdm_ray.py — the reference emits
magic-token JSON lines on worker stdout which a driver-side
``BarManager`` demultiplexes into real tqdm bars. Here worker bars
publish state records over the hub's pubsub plane (channel
``__tqdm__``) — the same transport worker logs already ride — and the
driver renders them; driver-local bars render directly. No dependency
on the real tqdm package.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import uuid as _uuid
from typing import Any, Dict, Iterable, Optional

_THROTTLE_S = 0.1
CHANNEL = "__tqdm__"

_mgr_lock = threading.Lock()
_manager: Optional["BarManager"] = None


def _get_manager() -> "BarManager":
    global _manager
    with _mgr_lock:
        if _manager is None:
            _manager = BarManager()
        return _manager


class BarManager:
    """Driver-side renderer: one status line per live bar.

    The reference stacks real tqdm instances by position; this renders
    equivalent `desc: n/total` lines, throttled, overwriting in place
    when stderr is a tty and falling back to plain prints otherwise.
    """

    def __init__(self):
        self._bars: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._last_draw = 0.0
        self._tty = sys.stderr.isatty()

    def process_state_update(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if rec.get("closed"):
                bar = self._bars.pop(rec["uuid"], None)
                if bar is not None:
                    self._draw(final=self._fmt(rec))
                return
            self._bars[rec["uuid"]] = rec
            now = time.monotonic()
            if now - self._last_draw >= _THROTTLE_S:
                self._last_draw = now
                self._draw()

    @staticmethod
    def _fmt(rec: Dict[str, Any]) -> str:
        total = rec.get("total")
        frac = f"{rec['x']}/{total}" if total else str(rec["x"])
        pid = rec.get("pid")
        src = f" (pid={pid})" if pid and pid != os.getpid() else ""
        return f"{rec.get('desc') or 'it'}{src}: {frac}"

    def _draw(self, final: Optional[str] = None) -> None:
        lines = [self._fmt(r) for r in self._bars.values()]
        if final is not None:
            sys.stderr.write(("\r" if self._tty else "") + final + "\n")
        elif self._tty and len(lines) == 1:
            sys.stderr.write("\r" + lines[0] + "\x1b[K")
        else:
            for line in lines:
                sys.stderr.write(line + "\n")
        sys.stderr.flush()


def _driver_subscribe(client) -> None:
    """Wired up by worker.init alongside the log subscription."""
    client.subscribe(CHANNEL, _get_manager().process_state_update)


class tqdm:
    """Drop-in subset of tqdm's API, safe inside remote tasks/actors."""

    def __init__(
        self,
        iterable: Optional[Iterable] = None,
        desc: str = "",
        total: Optional[int] = None,
        position: Optional[int] = None,
    ):
        self._iterable = iterable
        self._desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self._total = total
        self._position = position
        self._x = 0
        self._uuid = _uuid.uuid4().hex
        self._closed = False
        self._last_pub = 0.0
        self._publish(force=True)

    # -- tqdm API -----------------------------------------------------
    def update(self, n: int = 1) -> None:
        self._x += n
        self._publish()

    def set_description(self, desc: str) -> None:
        self._desc = desc
        self._publish()

    def refresh(self) -> None:
        self._publish(force=True)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._publish(force=True)

    def __iter__(self):
        assert self._iterable is not None, "no iterable passed to tqdm()"
        try:
            for item in self._iterable:
                yield item
                self.update(1)
        finally:
            self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- transport ----------------------------------------------------
    def _state(self) -> Dict[str, Any]:
        return {
            "uuid": self._uuid,
            "desc": self._desc,
            "total": self._total,
            "x": self._x,
            "pos": self._position,
            "pid": os.getpid(),
            "closed": self._closed,
        }

    def _publish(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_pub < _THROTTLE_S:
            return
        self._last_pub = now
        from ray_tpu._private import worker as _worker

        if _worker._is_worker and _worker.is_initialized():
            try:
                _worker.get_client().publish(CHANNEL, self._state())
                return
            except Exception:
                pass
        _get_manager().process_state_update(self._state())


def safe_print(*args, **kwargs) -> None:
    """Print without corrupting in-place bar redraws (reference
    tqdm_ray.safe_print): emit a newline first if a tty bar is live."""
    mgr = _manager
    if mgr is not None and mgr._tty and mgr._bars:
        sys.stderr.write("\n")
    print(*args, **kwargs)
