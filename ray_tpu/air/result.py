"""Result of a training/tuning run (parity: python/ray/air/result.py)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class Result:
    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Any]  # ray_tpu.train.Checkpoint
    error: Optional[Exception] = None
    path: Optional[str] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: Optional[List[Tuple[Any, Dict[str, Any]]]] = None
    _metrics_history: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return (self.metrics or {}).get("config")

    def get_best_checkpoint(self, metric: str, mode: str = "max"):
        if not self.best_checkpoints:
            return self.checkpoint
        sign = 1 if mode == "max" else -1
        best = max(
            (c for c in self.best_checkpoints if metric in c[1]),
            key=lambda c: sign * c[1][metric],
            default=None,
        )
        return best[0] if best else self.checkpoint

    def __repr__(self):
        err = f", error={type(self.error).__name__}" if self.error else ""
        return f"Result(metrics={self.metrics}, path={self.path!r}{err})"
