"""ray_tpu.air — shared configs and results for Train/Tune.

Parity: python/ray/air/ in the reference (config.py:103 ScalingConfig,
:398 FailureConfig, :448 CheckpointConfig, :597 RunConfig; Result in
air/result.py). TPU-native addition: ScalingConfig speaks chips and
slice topologies, not GPUs.
"""

from .config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from .result import Result

__all__ = [
    "CheckpointConfig",
    "FailureConfig",
    "RunConfig",
    "ScalingConfig",
    "Result",
]
