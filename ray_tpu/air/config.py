"""Run/scaling/failure/checkpoint configs.

Parity: python/ray/air/config.py (ScalingConfig :103, FailureConfig
:398, CheckpointConfig :448, RunConfig :597). Differences are
TPU-native: `use_tpu`/`topology` replace `use_gpu`/`accelerator_type`,
and a ScalingConfig maps onto gang placement over chips/slices.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union


@dataclass
class ScalingConfig:
    """How many workers, with what resources each.

    num_workers: training processes (one JAX process per host in
    multi-host pods; on one host usually 1 worker owning all chips).
    use_tpu: give each worker TPU chips. resources_per_worker overrides
    the per-worker resource dict. topology: slice topology string
    (e.g. "v5p-16") — workers gang-schedule onto one slice
    (reference analogue: TPU pod-name resources,
    python/ray/_private/accelerators/tpu.py:352-375).
    """

    num_workers: int = 1
    use_tpu: bool = False
    use_gpu: bool = False  # accepted for API parity; maps onto TPU=0
    tpu_chips_per_worker: Optional[int] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None
    trainer_resources: Optional[Dict[str, float]] = None
    # elastic training (reference: train/v2 ScalingPolicy + elastic
    # resize): when set, a gang that cannot be placed at num_workers
    # after a failure restarts at a smaller size (halving down to this
    # floor) instead of failing the run.
    min_workers: Optional[int] = None
    placement_timeout_s: float = 60.0

    def _resources_per_worker_not_none(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = float(self.tpu_chips_per_worker or 1)
        return res

    @property
    def num_tpus_per_worker(self) -> float:
        return self._resources_per_worker_not_none().get("TPU", 0.0)

    def as_placement_group_factory(self):
        from ..util.placement_group import placement_group

        bundles = [self._resources_per_worker_not_none() for _ in range(self.num_workers)]
        if self.trainer_resources:
            bundles = [dict(self.trainer_resources)] + bundles
        return lambda: placement_group(bundles, strategy=self.placement_strategy)

    @property
    def total_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self.trainer_resources or {})
        per_worker = self._resources_per_worker_not_none()
        for k, v in per_worker.items():
            out[k] = out.get(k, 0.0) + v * self.num_workers
        return out


@dataclass
class FailureConfig:
    """Retries on worker-group failure (reference :398). TPU gangs are
    all-or-nothing: any worker death fails the gang; the controller
    restarts the whole group from the latest checkpoint."""

    max_failures: int = 0
    fail_fast: bool = False


@dataclass
class CheckpointConfig:
    """Top-k retention (reference :448)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: Optional[bool] = None

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


@dataclass
class RunConfig:
    """Experiment-level config (reference :597)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Union[Dict[str, Any], Callable]] = None
    verbose: int = 1
    log_to_file: bool = False
    callbacks: Optional[List[Any]] = None

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = os.path.expanduser(
                os.environ.get("RAY_TPU_STORAGE_PATH", "~/ray_tpu_results")
            )
        if self.failure_config is None:
            self.failure_config = FailureConfig()
        if self.checkpoint_config is None:
            self.checkpoint_config = CheckpointConfig()
