"""Jupyter HTML reprs.

Parity: python/ray/widgets/ — the reference templates HTML cards for
``ray.init()`` context and datasets (widgets/render.py Template). Same
idea, no template files: small helpers that subsystems call from
``_repr_html_``.
"""

from __future__ import annotations

from html import escape
from typing import Any, Dict, List, Sequence

_CARD = (
    '<div style="border:1px solid #ddd;border-radius:6px;padding:10px 14px;'
    'display:inline-block;font-family:monospace;font-size:12px">'
    "<b>{title}</b>{body}</div>"
)


def table_html(rows: Dict[str, Any]) -> str:
    trs = "".join(
        f"<tr><td style='padding-right:12px;color:#666'>{escape(str(k))}</td>"
        f"<td>{escape(str(v))}</td></tr>"
        for k, v in rows.items()
    )
    return f"<table>{trs}</table>"


def card_html(title: str, rows: Dict[str, Any]) -> str:
    return _CARD.format(title=escape(title), body=table_html(rows))


def dataset_html(name: str, count, schema_names: Sequence[str], extra: Dict[str, Any]) -> str:
    rows: Dict[str, Any] = {"num_rows": count if count is not None else "?"}
    rows["schema"] = ", ".join(schema_names) if schema_names else "unknown"
    rows.update(extra)
    return card_html(name, rows)
