"""MARWIL: monotonic advantage re-weighted imitation learning.

Parity: python/ray/rllib/algorithms/marwil/ — offline learning from a
Dataset of (obs, actions, returns): a value head estimates V(s), and
the policy is cloned with per-sample weights exp(beta * advantage /
norm), so high-return actions dominate (beta=0 degenerates to BC —
same equivalence the reference documents). The advantage normalizer is
the running mean of squared advantages (the paper's c^2 estimate).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core import MLPSpec, forward, init_mlp_module


@dataclass
class MARWILConfig:
    lr: float = 1e-3
    beta: float = 1.0  # 0 = plain BC
    vf_coeff: float = 1.0
    moving_average_sqd_adv_norm_update_rate: float = 1e-2  # reference knob
    train_batch_size: int = 256
    hiddens: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def training(self, **kwargs) -> "MARWILConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown MARWIL training param {k!r}")
            setattr(self, k, v)
        return self

    def build_algo(self, obs_dim: int, num_actions: int) -> "MARWIL":
        return MARWIL(self, obs_dim, num_actions)


class MARWIL:
    def __init__(self, config: MARWILConfig, obs_dim: int, num_actions: int):
        import optax

        self.config = config
        self.spec = MLPSpec(obs_dim, num_actions, tuple(config.hiddens))
        self.params = init_mlp_module(
            jax.random.PRNGKey(config.seed), self.spec
        )
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        # moving average of squared advantages (weight normalizer)
        self.ma_sqd_adv = jnp.asarray(1.0, jnp.float32)
        beta = config.beta
        vf_coeff = config.vf_coeff
        rate = config.moving_average_sqd_adv_norm_update_rate

        def loss_fn(params, ma_sqd_adv, obs, actions, returns):
            logits, values = forward(params, obs)
            adv = returns - values
            # update the normalizer OUTSIDE the gradient
            adv_sg = jax.lax.stop_gradient(adv)
            new_ma = ma_sqd_adv + rate * (jnp.mean(adv_sg**2) - ma_sqd_adv)
            weights = jnp.exp(
                beta * adv_sg / jnp.sqrt(jnp.maximum(new_ma, 1e-8))
            )
            # clip for stability (reference clamps the exponent's output)
            weights = jnp.minimum(weights, 20.0)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, actions[:, None], axis=1)[:, 0]
            pi_loss = jnp.mean(jax.lax.stop_gradient(weights) * nll)
            vf_loss = jnp.mean(adv**2)
            return pi_loss + vf_coeff * vf_loss, (new_ma, pi_loss, vf_loss)

        @jax.jit
        def update(params, opt_state, ma_sqd_adv, obs, actions, returns):
            (loss, (new_ma, pi_loss, vf_loss)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, ma_sqd_adv, obs, actions, returns)
            updates, opt_state = self.optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, new_ma, loss, pi_loss, vf_loss

        self._update = update
        self.iteration = 0

    def train_on_dataset(self, dataset, *, epochs: int = 1) -> Dict[str, Any]:
        """Offline pass(es) over a Dataset with "obs", "actions" and
        "returns" columns (rllib/offline shape + MC returns)."""
        losses = []
        n = 0
        for _ in range(epochs):
            for batch in dataset.iter_batches(
                batch_size=self.config.train_batch_size, batch_format="numpy"
            ):
                actions = np.asarray(batch["actions"], np.int64)
                obs = np.asarray(batch["obs"], np.float32).reshape(
                    len(actions), -1
                )
                returns = np.asarray(batch["returns"], np.float32)
                (
                    self.params,
                    self.opt_state,
                    self.ma_sqd_adv,
                    loss,
                    _pi,
                    _vf,
                ) = self._update(
                    self.params, self.opt_state, self.ma_sqd_adv,
                    obs, actions, returns,
                )
                losses.append(float(loss))
                n += len(actions)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_samples_trained": n,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "ma_sqd_adv": float(self.ma_sqd_adv),
        }

    def compute_single_action(self, obs) -> int:
        logits, _ = forward(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(logits[0]))
