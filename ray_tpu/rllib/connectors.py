"""ConnectorV2: composable env→module / learner transform pipelines.

Parity: python/ray/rllib/connectors/ (connector_v2.py ConnectorV2 +
connector_pipeline_v2.py) — small reusable pieces that transform
batches on their way from the env into the module (obs preprocessing,
frame stacking) and from the rollout into the learner, instead of
per-algorithm hand-rolled preprocessing.

TPU-native shape: a connector maps a COLUMN BATCH (dict of numpy
arrays, batched across all (env, agent) pairs of one module) to a new
column batch. Keeping the transform outside jit and returning plain
arrays preserves the runner's one-jitted-forward-per-module property;
anything shape-static a connector does could later fold into the
jitted program itself.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ConnectorV2",
    "ConnectorPipelineV2",
    "FlattenObservations",
    "NormalizeObservations",
    "FrameStackObservations",
]


class ConnectorV2:
    """One transform stage (reference: connector_v2.py:66).

    `batch` is a dict of columns — at minimum {"obs": (B, ...)}; the
    context carries `keys` (the (env_idx, agent_id) pair per row, for
    stateful per-agent connectors) and `module_id`."""

    def __call__(self, batch: Dict[str, np.ndarray], *,
                 keys: Optional[Sequence[Tuple[int, Any]]] = None,
                 module_id: str = "default_policy",
                 **kwargs) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # output feature size for a given input size; pipelines use this to
    # derive the module's obs_dim (reference: connectors recompute the
    # observation space)
    def output_dim(self, in_dim: int) -> int:
        return in_dim

    def reset(self) -> None:
        """Drop per-episode state (called between episodes/fragments
        where relevant)."""

    def drop(self, keys: Sequence[Tuple[int, Any]]) -> None:
        """Drop per-(env, agent) state for finished episodes."""


class ConnectorPipelineV2(ConnectorV2):
    """Ordered composition (reference: connector_pipeline_v2.py)."""

    def __init__(self, connectors: Optional[List[ConnectorV2]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def __call__(self, batch, **ctx):
        for c in self.connectors:
            batch = c(batch, **ctx)
        return batch

    def output_dim(self, in_dim: int) -> int:
        for c in self.connectors:
            in_dim = c.output_dim(in_dim)
        return in_dim

    def reset(self) -> None:
        for c in self.connectors:
            c.reset()

    def drop(self, keys) -> None:
        for c in self.connectors:
            c.drop(keys)


class FlattenObservations(ConnectorV2):
    """(B, ...) obs -> (B, D) (reference:
    connectors/env_to_module/flatten_observations.py)."""

    def __call__(self, batch, **ctx):
        obs = np.asarray(batch["obs"])
        return dict(batch, obs=obs.reshape(obs.shape[0], -1))


class NormalizeObservations(ConnectorV2):
    """Running mean/std normalization (reference:
    connectors/env_to_module/mean_std_filter.py — Welford-style running
    moments, updated on every batch that flows through)."""

    def __init__(self, clip: float = 10.0, update: bool = True):
        self.clip = clip
        self.update = update
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, batch, *, peek: bool = False, **ctx):
        obs = np.asarray(batch["obs"], np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if self.update and not peek and len(flat):
            if self._mean is None:
                self._mean = np.zeros(flat.shape[1], np.float64)
                # zeros, not ones: _m2 is the running sum of squared
                # deviations — a ones seed adds a phantom unit of
                # variance per feature and biases early std estimates
                # upward (GL006)
                self._m2 = np.zeros(flat.shape[1], np.float64)
            # batched Chan's parallel-moments merge: one vectorized
            # update per batch instead of a per-row Python loop (this
            # runs in the rollout hot path)
            nb = float(len(flat))
            b_mean = flat.mean(axis=0, dtype=np.float64)
            b_m2 = ((flat - b_mean) ** 2).sum(axis=0, dtype=np.float64)
            delta = b_mean - self._mean
            tot = self._count + nb
            self._mean += delta * (nb / tot)
            self._m2 += b_m2 + delta**2 * (self._count * nb / tot)
            self._count = tot
        if self._mean is None or self._count < 2:
            return dict(batch, obs=flat)
        std = np.sqrt(self._m2 / max(self._count - 1.0, 1.0)) + 1e-8
        out = np.clip(
            (flat - self._mean) / std, -self.clip, self.clip
        ).astype(np.float32)
        return dict(batch, obs=out)

    def state(self) -> dict:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}


class FrameStackObservations(ConnectorV2):
    """Stack the last k observations per (env, agent) along the feature
    axis (reference: connectors/env_to_module/frame_stacking.py). Rows
    early in an episode repeat the first frame."""

    def __init__(self, num_frames: int = 4):
        if num_frames < 1:
            raise ValueError("num_frames must be >= 1")
        self.k = num_frames
        self._hist: Dict[Tuple[Any, Any], deque] = {}

    def __call__(self, batch, *, keys=None, peek: bool = False, **ctx):
        obs = np.asarray(batch["obs"], np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if keys is None:
            keys = [(0, i) for i in range(flat.shape[0])]
        rows = []
        for key, row in zip(keys, flat):
            h = self._hist.get(key)
            if peek:
                # bootstrap transforms must not advance episode state
                frames = (
                    [row] * self.k if h is None
                    else list(h)[1:] + [row]
                )
                rows.append(np.concatenate(frames))
                continue
            if h is None:
                h = self._hist[key] = deque(
                    [row] * self.k, maxlen=self.k
                )
            else:
                h.append(row)
            rows.append(np.concatenate(list(h)))
        return dict(batch, obs=np.stack(rows))

    def output_dim(self, in_dim: int) -> int:
        return in_dim * self.k

    def reset(self) -> None:
        self._hist.clear()

    def drop(self, keys) -> None:
        for key in keys:
            self._hist.pop(key, None)
