"""CQL: conservative Q-learning for offline RL (discrete actions).

Parity: python/ray/rllib/algorithms/cql/ — offline TD learning with the
conservative regularizer alpha * E[logsumexp_a Q(s,a) - Q(s, a_data)],
which pushes down Q on out-of-distribution actions so the greedy policy
stays inside the dataset's support. Data flows the rllib/offline way:
a Dataset of (obs, actions, rewards, next_obs, dones) transitions is
staged into the replay buffer and minibatched into one jitted update
(double-Q target + CQL penalty + Adam).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .dqn import _init_q_net, _q_values, double_q_target
from .replay_buffers import ReplayBuffer


@dataclass
class CQLConfig:
    lr: float = 3e-4
    gamma: float = 0.99
    cql_alpha: float = 1.0  # conservative penalty weight (min_q_weight)
    grad_clip: float = 10.0
    target_network_update_freq: int = 200
    train_batch_size: int = 256
    buffer_capacity: int = 1_000_000
    hiddens: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def training(self, **kwargs) -> "CQLConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown CQL training param {k!r}")
            setattr(self, k, v)
        return self

    def build_algo(self, obs_dim: int, num_actions: int) -> "CQL":
        return CQL(self, obs_dim, num_actions)


class CQL:
    def __init__(self, config: CQLConfig, obs_dim: int, num_actions: int):
        import optax

        from .core import MLPSpec

        self.config = config
        self.spec = MLPSpec(obs_dim, num_actions, tuple(config.hiddens))
        self.params = _init_q_net(jax.random.PRNGKey(config.seed), self.spec)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        self.opt_state = self.optimizer.init(self.params)
        gamma = config.gamma
        alpha = config.cql_alpha

        def loss_fn(params, target_params, batch):
            q = _q_values(params, batch["obs"])  # (B, A)
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1
            )[:, 0]
            target = double_q_target(
                params, target_params, batch, gamma=gamma, double_q=True
            )
            td = q_taken - target
            td_loss = jnp.mean(optax.huber_loss(td))
            # conservative penalty: push down the soft-max over ALL
            # actions, push up the dataset action
            cql_penalty = jnp.mean(
                jax.scipy.special.logsumexp(q, axis=1) - q_taken
            )
            return td_loss + alpha * cql_penalty, (td_loss, cql_penalty)

        @jax.jit
        def update(params, target_params, opt_state, batch):
            (loss, (td, pen)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, target_params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td, pen

        self._update = update
        self.buffer = ReplayBuffer(config.buffer_capacity, seed=config.seed)
        self.iteration = 0
        self._updates = 0

    def stage_dataset(self, dataset) -> int:
        """Load an offline transitions Dataset into the replay buffer.
        Fails loudly on overflow — silently ring-dropping offline rows
        would invalidate training without a trace."""
        n = 0
        for batch in dataset.iter_batches(batch_size=4096, batch_format="numpy"):
            staged = {
                "obs": np.asarray(batch["obs"], np.float32).reshape(
                    len(batch["actions"]), -1
                ),
                "actions": np.asarray(batch["actions"], np.int64),
                "rewards": np.asarray(batch["rewards"], np.float32),
                "next_obs": np.asarray(batch["next_obs"], np.float32).reshape(
                    len(batch["actions"]), -1
                ),
                "dones": np.asarray(batch["dones"], np.float32),
            }
            self.buffer.add(staged)
            n += len(staged["actions"])
            if n > self.config.buffer_capacity:
                raise ValueError(
                    f"offline dataset exceeds buffer_capacity="
                    f"{self.config.buffer_capacity}; raise it in CQLConfig"
                )
        return n

    def train(self, num_updates: int = 256) -> Dict[str, Any]:
        if num_updates <= 0:
            raise ValueError(f"num_updates must be positive, got {num_updates}")
        if not len(self.buffer):
            raise RuntimeError("stage_dataset() before train()")
        c = self.config
        loss = td = pen = float("nan")
        for _ in range(num_updates):
            batch = self.buffer.sample(c.train_batch_size)
            self.params, self.opt_state, loss, td, pen = self._update(
                self.params, self.target_params, self.opt_state, batch
            )
            self._updates += 1
            if self._updates % c.target_network_update_freq == 0:
                self.target_params = jax.tree.map(lambda x: x, self.params)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_updates_lifetime": self._updates,
            "loss": float(loss),
            "td_loss": float(td),
            "cql_penalty": float(pen),
        }

    def compute_single_action(self, obs) -> int:
        q = _q_values(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(q[0]))
