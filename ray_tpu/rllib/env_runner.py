"""EnvRunner: rollout-collection actors.

Parity: python/ray/rllib/env/single_agent_env_runner.py +
env_runner_group.py:71 — actors own gymnasium vector envs, receive
policy weights each iteration, and return fixed-length rollout batches
(the async actor fan-out pattern §2.5). Rollouts are plain numpy so the
learner can device_put them straight into HBM.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np


def substitute_final_obs(next_obs, term, trunc, infos) -> np.ndarray:
    """SAME_STEP autoreset returns the NEW episode's reset obs at done
    steps; replay-style transitions must store the true final obs
    (infos["final_obs"]) or the critic bootstraps into an unrelated
    state. Shared by the DQN and SAC runners."""
    final_obs = infos.get("final_obs")
    if final_obs is None:
        return next_obs
    done_idx = np.nonzero(np.logical_or(term, trunc))[0]
    if not len(done_idx):
        return next_obs
    out = next_obs.copy()
    for i in done_idx:
        if final_obs[i] is not None:
            out[i] = np.asarray(final_obs[i])
    return out


def merge_return_windows(latest_windows: Dict[int, list]) -> list:
    """Per-runner last-100 windows are cumulative: keep only the newest
    per runner (the dict values) and concat across runners — extending
    every round would double-count episodes."""
    return [r for window in latest_windows.values() for r in window]


class SingleAgentEnvRunner:
    def __init__(
        self,
        env_creator: Union[str, Callable],
        num_envs: int = 1,
        seed: Optional[int] = None,
        rollout_fragment_length: int = 128,
        gamma: float = 0.99,
    ):
        import gymnasium as gym

        if isinstance(env_creator, str):
            env_id = env_creator
            fns = [lambda: gym.make(env_id) for _ in range(num_envs)]
        else:
            fns = [env_creator for _ in range(num_envs)]
        # SAME_STEP autoreset: a done step immediately returns the reset
        # obs, with the true final obs in infos — so every recorded
        # transition is real. (gymnasium >=1.0 defaults to NEXT_STEP,
        # which inserts a filler transition per episode end that would
        # corrupt PPO's batch.)
        self.envs = gym.vector.SyncVectorEnv(
            fns, autoreset_mode=gym.vector.AutoresetMode.SAME_STEP
        )
        self.num_envs = num_envs
        self.fragment = rollout_fragment_length
        self.gamma = gamma
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.envs.reset(seed=seed)
        # episode-return bookkeeping
        self._ep_returns = np.zeros(num_envs)
        self._ep_lens = np.zeros(num_envs, dtype=np.int64)
        # trailing window only; a plain list leaks for the runner's
        # lifetime (GL005)
        self.completed_returns: deque = deque(maxlen=100)

    def obs_space_dim(self) -> int:
        return int(np.prod(self.envs.single_observation_space.shape))

    def num_actions(self) -> int:
        return int(self.envs.single_action_space.n)

    def sample(self, params: Dict[str, Any], rng_seed: int) -> Dict[str, np.ndarray]:
        """Collect one fragment with the given policy weights. Returns
        time-major batch {obs, actions, rewards, dones, logp, values,
        final_obs} + episode stats."""
        import jax

        from .core import sample_actions

        key = jax.random.PRNGKey(rng_seed)
        T, N = self.fragment, self.num_envs
        obs_buf = np.zeros((T, N) + self.envs.single_observation_space.shape, np.float32)
        act_buf = np.zeros((T, N), np.int64)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)

        obs = self.obs
        recent_returns: list = []
        for t in range(T):
            key, sub = jax.random.split(key)
            actions, logp, value = sample_actions(
                params, obs.astype(np.float32), sub
            )
            actions = np.asarray(actions)
            next_obs, rewards, term, trunc, infos = self.envs.step(actions)
            done = np.logical_or(term, trunc)
            rewards = np.asarray(rewards, np.float32).copy()
            # episode stats must see the RAW env rewards — the truncation
            # bootstrap below is a learning-signal adjustment only and
            # must not inflate episode_return_mean
            raw_rewards = rewards.copy()
            # time-limit truncation is NOT termination: bootstrap the
            # cut-off return from V(final_obs) (standard PPO truncation
            # handling; the GAE then treats the step as terminal)
            if np.any(trunc):
                from .core import values_only

                final_obs = infos.get("final_obs")
                idx = np.nonzero(trunc)[0]
                fo = np.stack(
                    [
                        np.asarray(
                            final_obs[i]
                            if final_obs is not None and final_obs[i] is not None
                            else next_obs[i],
                            np.float32,
                        ).reshape(-1)
                        for i in idx
                    ]
                )
                v_fin = np.asarray(values_only(params, fo))
                rewards[idx] += self.gamma * v_fin
            obs_buf[t] = obs
            act_buf[t] = actions
            rew_buf[t] = rewards
            done_buf[t] = done
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            # track episode returns (vector env auto-resets)
            self._ep_returns += raw_rewards
            self._ep_lens += 1
            for i in np.nonzero(done)[0]:
                self.completed_returns.append(float(self._ep_returns[i]))
                recent_returns.append(float(self._ep_returns[i]))
                self._ep_returns[i] = 0.0
                self._ep_lens[i] = 0
            obs = next_obs
        self.obs = obs
        stats_returns = list(self.completed_returns)
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "logp": logp_buf,
            "values": val_buf,
            "final_obs": obs.astype(np.float32),
            "episode_returns": np.asarray(stats_returns, np.float32),
            # episodes completed during THIS fragment only. The window
            # above is a trailing deque(maxlen=100): until 100 episodes
            # have finished it is a LIFETIME mean that still contains
            # the random policy's first episodes, so it lags actual
            # learning by many iterations — short-horizon callers
            # (tests, early-stopping) should read this key instead.
            "episode_returns_recent": np.asarray(recent_returns, np.float32),
        }
