"""Multi-agent RL: env API, episodes, env runner, and PPO learner.

Parity: python/ray/rllib/env/multi_agent_env.py (MultiAgentEnv,
make_multi_agent), multi_agent_episode.py (per-agent trajectories with
an env-step clock), multi_agent_env_runner.py (per-module batched
inference over the currently-acting agents), and the
policies/policy_mapping_fn surface of algorithm_config.multi_agent().

TPU-native differences:
- Inference batches across envs AND agents per module, so each module
  does ONE jitted forward per env step regardless of agent count.
- The learner consumes variable-length per-agent sequences by computing
  GAE host-side (numpy) and padding the flat per-module batch to a
  fixed bucket with a loss mask — static shapes, one XLA executable per
  (module spec, bucket), instead of the reference's dynamic torch
  batches.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "MultiAgentEnv",
    "make_multi_agent",
    "MultiAgentEpisode",
    "MultiAgentEnvRunner",
    "MultiAgentAlgorithm",
]


class MultiAgentEnv:
    """An environment hosting multiple independently-acting agents.

    Parity: rllib/env/multi_agent_env.py:29. Agents are string ids;
    `step` takes/returns per-agent dicts; the reserved "__all__" key in
    the terminated/truncated dicts signals episode end. Agents may act
    intermittently (turn-based envs simply omit non-acting agents from
    the obs dict).
    """

    # All agents that may ever appear; fixed for the env's lifetime.
    possible_agents: List[str] = []
    # Agents currently active (may change during an episode).
    agents: List[str] = []
    observation_spaces: Optional[Dict[str, Any]] = None
    action_spaces: Optional[Dict[str, Any]] = None

    def get_observation_space(self, agent_id: str):
        return (self.observation_spaces or {})[agent_id]

    def get_action_space(self, agent_id: str):
        return (self.action_spaces or {})[agent_id]

    def reset(self, *, seed: Optional[int] = None, options: Optional[dict] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]) -> Tuple[
        Dict[str, Any], Dict[str, float], Dict[str, bool],
        Dict[str, bool], Dict[str, Any],
    ]:
        raise NotImplementedError

    def close(self) -> None:
        pass


def make_multi_agent(env_name_or_creator) -> type:
    """Wrap a single-agent gym env into an N-agent MultiAgentEnv of
    independent copies (reference: multi_agent_env.py make_multi_agent —
    the standard multi-agent CartPole test env). Config: {"num_agents"}.
    """

    class IndependentMultiAgent(MultiAgentEnv):
        def __init__(self, config: Optional[dict] = None):
            import gymnasium as gym

            config = config or {}
            n = int(config.get("num_agents", 2))
            if isinstance(env_name_or_creator, str):
                self.envs = [gym.make(env_name_or_creator) for _ in range(n)]
            else:
                self.envs = [env_name_or_creator(config) for _ in range(n)]
            self.possible_agents = [f"agent_{i}" for i in range(n)]
            self.agents = list(self.possible_agents)
            self.observation_spaces = {
                a: e.observation_space
                for a, e in zip(self.possible_agents, self.envs)
            }
            self.action_spaces = {
                a: e.action_space
                for a, e in zip(self.possible_agents, self.envs)
            }
            self._done: Dict[str, bool] = {}

        def reset(self, *, seed=None, options=None):
            self.agents = list(self.possible_agents)
            self._done = {a: False for a in self.possible_agents}
            obs, infos = {}, {}
            for i, (a, e) in enumerate(zip(self.possible_agents, self.envs)):
                o, inf = e.reset(seed=None if seed is None else seed + i,
                                 options=options)
                obs[a], infos[a] = o, inf
            return obs, infos

        def step(self, action_dict):
            obs, rew, term, trunc, infos = {}, {}, {}, {}, {}
            for a, act in action_dict.items():
                if self._done.get(a):
                    continue
                e = self.envs[self.possible_agents.index(a)]
                o, r, te, tr, inf = e.step(act)
                obs[a], rew[a] = o, float(r)
                term[a], trunc[a], infos[a] = bool(te), bool(tr), inf
                if te or tr:
                    self._done[a] = True
            self.agents = [a for a in self.possible_agents if not self._done[a]]
            term["__all__"] = all(self._done.values())
            trunc["__all__"] = False
            return obs, rew, term, trunc, infos

        def close(self):
            for e in self.envs:
                e.close()

    return IndependentMultiAgent


class _AgentTrack:
    """Per-agent trajectory inside one MultiAgentEpisode fragment."""

    __slots__ = ("obs", "proc_obs", "actions", "rewards", "logp", "values",
                 "terminated", "truncated", "ep_return")

    def __init__(self):
        self.obs: List[np.ndarray] = []
        # what the MODULE saw (post env→module connectors) — the
        # learner must train on these, not the raw env obs
        self.proc_obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.rewards: List[float] = []
        self.logp: List[float] = []
        self.values: List[float] = []
        self.terminated = False
        self.truncated = False
        self.ep_return = 0.0


class MultiAgentEpisode:
    """Per-agent trajectories sharing one env-step clock.

    Parity: rllib/env/multi_agent_episode.py (the essentials: per-agent
    obs/action/reward columns, agents_to_act from the latest obs dict,
    per-agent terminations plus "__all__", and cut() for fragment
    continuation). Rewards arriving for a non-acting agent accumulate
    onto its last action, as in the reference's agent-step mapping.
    """

    def __init__(self, agent_to_module: Callable[[str], str]):
        self._agent_to_module = agent_to_module
        self.tracks: Dict[str, _AgentTrack] = {}
        self.module_of: Dict[str, str] = {}
        self.agents_to_act: List[str] = []
        self.env_t = 0
        self.is_done = False

    def module_for(self, agent_id: str) -> str:
        m = self.module_of.get(agent_id)
        if m is None:
            m = self.module_of[agent_id] = self._agent_to_module(agent_id)
        return m

    def _track(self, agent_id: str) -> _AgentTrack:
        t = self.tracks.get(agent_id)
        if t is None:
            t = self.tracks[agent_id] = _AgentTrack()
        return t

    def add_env_reset(self, obs: Dict[str, Any], infos: Dict[str, Any]):
        for a, o in obs.items():
            self._track(a).obs.append(np.asarray(o, np.float32).reshape(-1))
        self.agents_to_act = list(obs.keys())

    def add_action(self, agent_id: str, action: int, logp: float,
                   value: float, proc_obs: Optional[np.ndarray] = None):
        t = self.tracks[agent_id]
        if proc_obs is None:
            proc_obs = t.obs[len(t.actions)]
        t.proc_obs.append(np.asarray(proc_obs, np.float32))
        t.actions.append(int(action))
        t.logp.append(float(logp))
        t.values.append(float(value))
        t.rewards.append(0.0)

    def add_env_step(self, obs, rewards, terms, truncs, infos):
        self.env_t += 1
        for a, r in rewards.items():
            t = self._track(a)
            if t.rewards:
                t.rewards[-1] += float(r)
            t.ep_return += float(r)
        for a, o in obs.items():
            t = self._track(a)
            if not (t.terminated or t.truncated):
                t.obs.append(np.asarray(o, np.float32).reshape(-1))
        all_done = terms.get("__all__", False) or truncs.get("__all__", False)
        for a, t in self.tracks.items():
            if terms.get(a) or (all_done and terms.get("__all__", False)):
                t.terminated = True
            elif truncs.get(a) or all_done:
                t.truncated = True
        self.is_done = all_done
        self.agents_to_act = [
            a for a in obs
            if not (self.tracks[a].terminated or self.tracks[a].truncated)
        ]

    def total_return(self) -> float:
        return sum(t.ep_return for t in self.tracks.values())

    def extract_sequences(self) -> Dict[str, List[dict]]:
        """Per-module list of per-agent sequence dicts for the learner.
        A sequence bootstraps from its final obs unless terminated."""
        out: Dict[str, List[dict]] = {}
        for a, t in self.tracks.items():
            n = len(t.actions)
            if n == 0:
                continue
            final_obs = t.obs[n] if len(t.obs) > n else None
            seq = {
                "obs": np.stack(t.proc_obs[:n]),
                "actions": np.asarray(t.actions, np.int64),
                "rewards": np.asarray(t.rewards, np.float32),
                "logp": np.asarray(t.logp, np.float32),
                "values": np.asarray(t.values, np.float32),
                "terminated": t.terminated,
                "final_obs": final_obs,
                "agent_id": a,
            }
            out.setdefault(self.module_for(a), []).append(seq)
        return out

    def cut(self) -> "MultiAgentEpisode":
        """Continuation episode carrying live agents' last obs (the
        reference's MultiAgentEpisode.cut): trajectory buffers reset,
        episode-return accounting carries over."""
        nxt = MultiAgentEpisode(self._agent_to_module)
        nxt.env_t = self.env_t
        nxt.module_of = dict(self.module_of)
        for a, t in self.tracks.items():
            if t.terminated or t.truncated:
                continue
            n = len(t.actions)
            if len(t.obs) > n:
                nt = nxt._track(a)
                nt.obs.append(t.obs[n])
                nt.ep_return = t.ep_return
        nxt.agents_to_act = [
            a for a in self.agents_to_act if a in nxt.tracks
        ]
        return nxt


class MultiAgentEnvRunner:
    """Rollout actor for MultiAgentEnv (reference:
    multi_agent_env_runner.py:61). Owns num_envs env copies; each env
    step groups the currently-acting agents of ALL envs by module and
    runs one jitted forward per module."""

    def __init__(
        self,
        env_creator,
        policy_mapping_fn: Optional[Callable[[str, Any], str]] = None,
        env_config: Optional[dict] = None,
        num_envs: int = 1,
        seed: Optional[int] = None,
        rollout_fragment_length: int = 128,
        env_to_module_connector: Optional[Callable] = None,
    ):
        if isinstance(env_creator, str):
            raise ValueError(
                "multi-agent env must be a MultiAgentEnv subclass or "
                "callable(config) -> MultiAgentEnv"
            )
        mk = (env_creator if not isinstance(env_creator, type)
              else (lambda cfg: env_creator(cfg)))
        self.envs = [mk(env_config or {}) for _ in range(num_envs)]
        self.num_envs = num_envs
        self.fragment = rollout_fragment_length
        self._mapping = policy_mapping_fn or (lambda aid, ep=None: "default_policy")
        self.seed = seed
        self._ep_seed = 0 if seed is None else seed
        self.episodes: List[Optional[MultiAgentEpisode]] = [None] * num_envs
        # bounded: only the trailing window is ever reported, and a
        # plain list leaks for the runner's lifetime (GL005)
        self.completed_returns: deque = deque(maxlen=100)
        self._needs_reset = True
        # per-module env→module connector pipelines (reference:
        # config.env_to_module_connector building ConnectorV2 stacks)
        self._conn_builder = env_to_module_connector
        self._conns: Dict[str, Any] = {}

    def _connector(self, module_id: str):
        if self._conn_builder is None:
            return None
        conn = self._conns.get(module_id)
        if conn is None:
            conn = self._conns[module_id] = self._conn_builder()
        return conn

    # ---- space discovery (driver builds module specs from this)
    def module_specs(self) -> Dict[str, Tuple[int, int]]:
        env = self.envs[0]
        specs: Dict[str, Tuple[int, int]] = {}
        for a in env.possible_agents:
            m = self._mapping(a, None)
            dim = int(np.prod(env.get_observation_space(a).shape))
            conn = self._connector(m)
            if conn is not None:
                dim = int(conn.output_dim(dim))
            n_act = int(env.get_action_space(a).n)
            prev = specs.get(m)
            if prev is not None and prev != (dim, n_act):
                raise ValueError(
                    f"module {m!r} maps agents with mismatched spaces: "
                    f"{prev} vs {(dim, n_act)}"
                )
            specs[m] = (dim, n_act)
        return specs

    def _reset_env(self, i: int):
        ep = MultiAgentEpisode(lambda aid: self._mapping(aid, None))
        self._ep_seed += 1
        obs, infos = self.envs[i].reset(seed=self._ep_seed * 10007)
        ep.add_env_reset(obs, infos)
        self.episodes[i] = ep
        return ep

    def _emit_sequences(self, env_i: int, ep: MultiAgentEpisode,
                        sequences: Dict[str, List[dict]]) -> None:
        """Collect a (finished or cut) episode's sequences, running
        bootstrap obs through the connectors in peek mode (state must
        not advance — the same obs re-enters the pipeline as the next
        fragment's first inference input)."""
        for mid, seqs in ep.extract_sequences().items():
            conn = self._connector(mid)
            if conn is not None:
                for s in seqs:
                    if s["final_obs"] is not None:
                        s["final_obs"] = conn(
                            {"obs": np.asarray(s["final_obs"])[None]},
                            keys=[(env_i, s["agent_id"])],
                            module_id=mid,
                            peek=True,
                        )["obs"][0]
            sequences.setdefault(mid, []).extend(seqs)

    def sample(self, params_by_module: Dict[str, Any], rng_seed: int
               ) -> Dict[str, Any]:
        """Collect one fragment. Returns {"sequences": {module: [seq]},
        "episode_returns": [...], "env_steps": int}."""
        import jax

        from .core import sample_actions

        key = jax.random.PRNGKey(rng_seed)
        if self._needs_reset:
            for i in range(self.num_envs):
                self._reset_env(i)
            self._needs_reset = False
        sequences: Dict[str, List[dict]] = {}
        env_steps = 0
        for _t in range(self.fragment):
            # group (env_idx, agent) by module over all envs
            by_module: Dict[str, List[Tuple[int, str, np.ndarray]]] = {}
            for i, ep in enumerate(self.episodes):
                for a in ep.agents_to_act:
                    tr = ep.tracks[a]
                    by_module.setdefault(ep.module_for(a), []).append(
                        (i, a, tr.obs[len(tr.actions)])
                    )
            actions_for_env: List[Dict[str, int]] = [
                {} for _ in range(self.num_envs)
            ]
            for mid, items in by_module.items():
                obs_batch = np.stack([o for _, _, o in items])
                conn = self._connector(mid)
                if conn is not None:
                    obs_batch = conn(
                        {"obs": obs_batch},
                        keys=[(i, a) for i, a, _ in items],
                        module_id=mid,
                    )["obs"]
                key, sub = jax.random.split(key)
                acts, logp, vals = sample_actions(
                    params_by_module[mid], obs_batch, sub
                )
                acts = np.asarray(acts)
                logp = np.asarray(logp)
                vals = np.asarray(vals)
                for j, (i, a, _) in enumerate(items):
                    self.episodes[i].add_action(
                        a, acts[j], logp[j], vals[j],
                        proc_obs=obs_batch[j],
                    )
                    actions_for_env[i][a] = int(acts[j])
            for i, ep in enumerate(self.episodes):
                if not actions_for_env[i]:
                    continue
                obs, rew, term, trunc, infos = self.envs[i].step(
                    actions_for_env[i]
                )
                env_steps += 1
                ep.add_env_step(obs, rew, term, trunc, infos)
                if ep.is_done:
                    self.completed_returns.append(ep.total_return())
                    self._emit_sequences(i, ep, sequences)
                    if self._conns:
                        done_keys = [(i, a) for a in ep.tracks]
                        for conn in self._conns.values():
                            conn.drop(done_keys)
                    self._reset_env(i)
        # fragment cut: emit partial sequences, carry live episodes over
        # (connector state persists — the episodes continue)
        for i, ep in enumerate(self.episodes):
            self._emit_sequences(i, ep, sequences)
            self.episodes[i] = ep.cut()
        return {
            "sequences": sequences,
            "episode_returns": np.asarray(
                list(self.completed_returns), np.float32
            ),
            "env_steps": env_steps,
        }


# ------------------------------------------------------------- learner
_FLAT_UPDATE_CACHE: dict = {}


def make_flat_ppo_update(config, spec, bucket: int):
    """Jitted clipped-surrogate update over a FLAT padded batch
    {obs (B,D), actions, logp_old, advantages, value_targets,
    mask (B,)} with B == bucket. Mask zeroes padded rows out of every
    mean, so one executable serves any real batch size ≤ bucket."""
    import jax
    import jax.numpy as jnp
    import optax

    from .core import forward

    cache_key = (
        config.lr, config.clip_param, config.vf_loss_coeff,
        config.entropy_coeff, config.num_epochs, config.minibatch_size,
        config.grad_clip, spec, bucket,
    )
    cached = _FLAT_UPDATE_CACHE.get(cache_key)
    if cached is not None:
        return cached

    optimizer = optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.adam(config.lr),
    )

    def masked_mean(x, m):
        return jnp.sum(x * m) / jnp.maximum(jnp.sum(m), 1.0)

    def loss_fn(params, batch):
        logits, values = forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=-1
        )[:, 0]
        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - config.clip_param, 1 + config.clip_param) * adv,
        )
        m = batch["mask"]
        pi_loss = -masked_mean(surr, m)
        vf_loss = masked_mean((values - batch["value_targets"]) ** 2, m)
        entropy = masked_mean(
            -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1), m
        )
        total = (
            pi_loss
            + config.vf_loss_coeff * vf_loss
            - config.entropy_coeff * entropy
        )
        return total, {
            "policy_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy,
        }

    mb = min(config.minibatch_size, bucket)
    n_mb = max(1, bucket // mb)

    @jax.jit
    def update(params, opt_state, flat, rng):
        def epoch(carry, key):
            params, opt_state = carry
            perm = jax.random.permutation(key, bucket)

            def minibatch(carry, idx):
                params, opt_state = carry
                mb_idx = jax.lax.dynamic_slice_in_dim(perm, idx * mb, mb)
                batch = {k: v[mb_idx] for k, v in flat.items()}
                (_, metrics), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params, batch)
                updates, opt_state = optimizer.update(
                    grads, opt_state, params
                )
                params = optax.apply_updates(params, updates)
                return (params, opt_state), metrics

            (params, opt_state), metrics = jax.lax.scan(
                minibatch, (params, opt_state), jnp.arange(n_mb)
            )
            return (params, opt_state), metrics

        keys = jax.random.split(rng, config.num_epochs)
        (params, opt_state), metrics = jax.lax.scan(
            epoch, (params, opt_state), keys
        )
        metrics = jax.tree.map(lambda m: m.mean(), metrics)
        return params, opt_state, metrics

    _FLAT_UPDATE_CACHE[cache_key] = (optimizer, update)
    return optimizer, update


def _gae_flat(seqs: List[dict], bootstrap: np.ndarray, gamma: float,
              lam: float) -> Dict[str, np.ndarray]:
    """Host-side GAE over variable-length sequences -> one flat batch."""
    obs, actions, logp, advs, vtargs = [], [], [], [], []
    for s, bv in zip(seqs, bootstrap):
        r, v = s["rewards"], s["values"]
        n = len(r)
        adv = np.zeros(n, np.float32)
        next_adv = 0.0
        next_v = 0.0 if s["terminated"] else float(bv)
        for t in range(n - 1, -1, -1):
            delta = r[t] + gamma * next_v - v[t]
            adv[t] = delta + gamma * lam * next_adv
            next_adv = adv[t]
            next_v = v[t]
        obs.append(s["obs"])
        actions.append(s["actions"])
        logp.append(s["logp"])
        advs.append(adv)
        vtargs.append(adv + v)
    return {
        "obs": np.concatenate(obs),
        "actions": np.concatenate(actions),
        "logp_old": np.concatenate(logp),
        "advantages": np.concatenate(advs),
        "value_targets": np.concatenate(vtargs),
    }


class MultiAgentAlgorithm:
    """PPO training driver over a MultiAgentEnv (reference:
    algorithm.py training_step with a MultiRLModule): one param/optimizer
    pytree per module, rollouts fanned out to MultiAgentEnvRunner
    actors, and one masked flat update per module per iteration."""

    def __init__(self, config):
        import jax

        import ray_tpu

        from .core import MLPSpec, init_mlp_module

        if config.env is None:
            raise ValueError("config.environment(env) is required")
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.config = config
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self.env_runners = [
            runner_cls.remote(
                config.env,
                config.policy_mapping_fn,
                config.env_config,
                config.num_envs_per_env_runner,
                config.seed + 1000 * i,
                config.rollout_fragment_length,
                getattr(config, "env_to_module_connector", None),
            )
            for i in range(config.num_env_runners)
        ]
        specs = ray_tpu.get(self.env_runners[0].module_specs.remote())
        if config.policies:
            missing = set(config.policies) - set(specs)
            if missing:
                raise ValueError(
                    f"policies {sorted(missing)} are never produced by "
                    f"policy_mapping_fn (got {sorted(specs)})"
                )
        self.module_specs = {
            m: MLPSpec(dim, n_act, tuple(config.hiddens))
            for m, (dim, n_act) in specs.items()
        }
        key = jax.random.PRNGKey(config.seed)
        self.params: Dict[str, Any] = {}
        self.opt_states: Dict[str, Any] = {}
        self._optimizers: Dict[str, Any] = {}
        for m, spec in sorted(self.module_specs.items()):
            key, sub = jax.random.split(key)
            self.params[m] = init_mlp_module(sub, spec)
        self._rng = jax.random.PRNGKey(config.seed + 1)
        self.iteration = 0
        self._timesteps = 0

    def _bucket(self, n: int) -> int:
        mb = self.config.minibatch_size
        unit = max(mb, 256)
        return max(unit, int(math.ceil(n / unit)) * unit)

    def train(self) -> Dict[str, Any]:
        import jax

        import ray_tpu

        from .core import values_only

        host_params = {
            m: jax.tree.map(np.asarray, p) for m, p in self.params.items()
        }
        rollouts = ray_tpu.get([
            r.sample.remote(
                host_params, self.config.seed + self.iteration * 97 + i
            )
            for i, r in enumerate(self.env_runners)
        ])
        result: Dict[str, Any] = {}
        metrics_by_module: Dict[str, Dict[str, float]] = {}
        for mid, spec in self.module_specs.items():
            seqs = [
                s for ro in rollouts
                for s in ro["sequences"].get(mid, [])
            ]
            if not seqs:
                continue
            # bootstrap values for non-terminated sequences in one
            # jitted batch
            boot = np.zeros(len(seqs), np.float32)
            need = [
                (i, s["final_obs"]) for i, s in enumerate(seqs)
                if not s["terminated"] and s["final_obs"] is not None
            ]
            if need:
                fo = np.stack([o for _, o in need])
                v = np.asarray(values_only(self.params[mid], fo))
                for (i, _), vi in zip(need, v):
                    boot[i] = vi
            flat = _gae_flat(
                seqs, boot, self.config.gamma, self.config.lambda_
            )
            learner_conn = getattr(self.config, "learner_connector", None)
            if learner_conn is not None:
                # learner-side ConnectorV2 stage (reference:
                # connectors/learner/) — transforms the flat batch
                # before it enters the jitted update
                flat = learner_conn(flat, module_id=mid)
            n = len(flat["actions"])
            a = flat["advantages"]
            flat["advantages"] = (a - a.mean()) / (a.std() + 1e-8)
            bucket = self._bucket(n)
            mask = np.zeros(bucket, np.float32)
            mask[:n] = 1.0
            padded = {
                k: np.concatenate(
                    [v, np.zeros((bucket - n,) + v.shape[1:], v.dtype)]
                )
                for k, v in flat.items()
            }
            padded["mask"] = mask
            optimizer, update = make_flat_ppo_update(
                self.config, spec, bucket
            )
            if mid not in self.opt_states:
                self._optimizers[mid] = optimizer
                self.opt_states[mid] = optimizer.init(self.params[mid])
            self._rng, sub = jax.random.split(self._rng)
            self.params[mid], self.opt_states[mid], metrics = update(
                self.params[mid], self.opt_states[mid], padded, sub
            )
            metrics_by_module[mid] = {
                k: float(v) for k, v in metrics.items()
            }
            self._timesteps += n
        self.iteration += 1
        ep_returns = np.concatenate(
            [ro["episode_returns"] for ro in rollouts]
        )
        result.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "episode_return_mean": (
                float(ep_returns.mean()) if len(ep_returns) else float("nan")
            ),
            "num_episodes": int(len(ep_returns)),
            "learner": metrics_by_module,
        })
        return result

    def compute_single_action(self, obs, policy_id: str = "default_policy") -> int:
        import jax.numpy as jnp

        from .core import forward

        logits, _ = forward(
            self.params[policy_id],
            jnp.asarray(obs, jnp.float32).reshape(1, -1),
        )
        return int(jnp.argmax(logits[0]))

    def save(self, checkpoint_dir: str) -> str:
        import os
        import pickle

        import jax

        os.makedirs(checkpoint_dir, exist_ok=True)
        state = {
            "params": {
                m: jax.tree.map(np.asarray, p)
                for m, p in self.params.items()
            },
            "opt_states": {
                m: jax.tree.map(np.asarray, s)
                for m, s in self.opt_states.items()
            },
            "iteration": self.iteration,
            "timesteps": self._timesteps,
        }
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = state["params"]
        self.opt_states = state["opt_states"]
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]

    def stop(self) -> None:
        import ray_tpu

        for r in self.env_runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.env_runners = []
