"""IMPALA: async actor-learner with V-trace off-policy correction.

Parity: python/ray/rllib/algorithms/impala/ — EnvRunner actors sample
continuously with (slightly stale) behavior policies while the learner
consumes completed rollouts as they arrive; V-trace (Espeholt et al.
2018) corrects the off-policyness. TPU-native shape (§2.5): the entire
V-trace + SGD update is one jitted program; asynchrony lives in the
actor fan-out (`ray_tpu.wait` on whichever runner finishes first), not
in framework queue threads.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from .core import MLPSpec, forward


@dataclass
class IMPALAConfig:
    """Builder (reference: impala/impala.py IMPALAConfig)."""

    env: Optional[Union[str, Callable]] = None
    num_env_runners: int = 2
    num_envs_per_env_runner: int = 2
    rollout_fragment_length: int = 64
    lr: float = 5e-3
    gamma: float = 0.99
    vtrace_clip_rho: float = 1.0
    vtrace_clip_c: float = 1.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 1.0
    # learner updates consumed per train() iteration (each is one
    # runner's completed rollout — the async unit)
    updates_per_iteration: int = 4
    hiddens: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def environment(self, env) -> "IMPALAConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None) -> "IMPALAConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "IMPALAConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA training param {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed=None) -> "IMPALAConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build_algo(self):
        return IMPALA(self)

    build = build_algo


def vtrace(
    behavior_logp, target_logp, rewards, dones, values, bootstrap_value,
    *, gamma, clip_rho, clip_c,
):
    """V-trace targets (Espeholt et al. 2018, eqs. 1-2). All inputs
    time-major (T, B); returns (vs (T, B), pg_advantages (T, B))."""
    rho = jnp.exp(target_logp - behavior_logp)
    rho_c = jnp.minimum(clip_rho, rho)
    c = jnp.minimum(clip_c, rho)
    nonterminal = 1.0 - dones
    values_tp1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = rho_c * (rewards + gamma * nonterminal * values_tp1 - values)

    def step(acc, xs):
        delta_t, c_t, nt_t = xs
        acc = delta_t + gamma * nt_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        step,
        jnp.zeros_like(bootstrap_value),
        (deltas, c, nonterminal),
        reverse=True,
    )
    vs = values + vs_minus_v
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rho_c * (rewards + gamma * nonterminal * vs_tp1 - values)
    return vs, pg_adv


_UPDATE_CACHE: dict = {}


def make_impala_loss(config, spec: MLPSpec):
    """The V-trace loss as a standalone ``loss_fn(params, batch) ->
    (total, metrics)`` over a time-major batch {obs, actions, rewards,
    dones, logp_mu, final_obs}. ``config`` duck-types IMPALAConfig
    (gamma/vtrace clips/vf_loss_coeff/entropy_coeff) — the Podracer
    learners reuse this loss inside their own jitted programs (Anakin
    inlines it into the fused superstep; Sebulba wraps it in a
    shard_map over the learner collective mesh)."""

    def loss_fn(params, batch):
        T, B = batch["actions"].shape
        logits, values = forward(params, batch["obs"])  # (T, B, A), (T, B)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        bootstrap = forward(params, batch["final_obs"])[1]  # (B,)
        vs, pg_adv = vtrace(
            batch["logp_mu"], jax.lax.stop_gradient(logp),
            batch["rewards"], batch["dones"],
            jax.lax.stop_gradient(values), jax.lax.stop_gradient(bootstrap),
            gamma=config.gamma,
            clip_rho=config.vtrace_clip_rho,
            clip_c=config.vtrace_clip_c,
        )
        pi_loss = -jnp.mean(jax.lax.stop_gradient(pg_adv) * logp)
        vf_loss = jnp.mean((values - jax.lax.stop_gradient(vs)) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = (
            pi_loss
            + config.vf_loss_coeff * vf_loss
            - config.entropy_coeff * entropy
        )
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": jnp.mean(
                jnp.exp(jax.lax.stop_gradient(logp) - batch["logp_mu"])
            ),
        }

    return loss_fn


def make_impala_update(config: IMPALAConfig, spec: MLPSpec):
    """(optimizer, jitted update) — V-trace loss + one SGD step over a
    single runner's rollout. Cached per (hyperparams, spec)."""
    import optax

    key = (
        config.lr, config.gamma, config.vtrace_clip_rho,
        config.vtrace_clip_c, config.vf_loss_coeff, config.entropy_coeff,
        config.grad_clip, spec,
    )
    cached = _UPDATE_CACHE.get(key)
    if cached is not None:
        return cached

    optimizer = optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.adam(config.lr),
    )

    loss_fn = make_impala_loss(config, spec)

    @jax.jit
    def update(params, opt_state, batch):
        (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    _UPDATE_CACHE[key] = (optimizer, update)
    return optimizer, update


class IMPALA:
    """Async actor-learner driver (reference: impala.py training_step —
    sample non-blockingly from whichever runner is done, update, push
    fresh weights back to THAT runner only)."""

    def __init__(self, config: IMPALAConfig):
        import numpy as np

        import ray_tpu

        from .core import init_mlp_module
        from .env_runner import SingleAgentEnvRunner

        if config.env is None:
            raise ValueError("config.environment(env) is required")
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.config = config
        self._ray = ray_tpu
        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        self.env_runners = [
            runner_cls.remote(
                config.env,
                config.num_envs_per_env_runner,
                config.seed + 1000 * i,
                config.rollout_fragment_length,
                config.gamma,
            )
            for i in range(config.num_env_runners)
        ]
        obs_dim = ray_tpu.get(self.env_runners[0].obs_space_dim.remote())
        num_actions = ray_tpu.get(self.env_runners[0].num_actions.remote())
        self.spec = MLPSpec(obs_dim, num_actions, tuple(config.hiddens))
        self.params = init_mlp_module(jax.random.PRNGKey(config.seed), self.spec)
        self.optimizer, self._update = self._make_update(config, self.spec)
        self.opt_state = self.optimizer.init(self.params)
        self.iteration = 0
        self._timesteps = 0
        self._seed_counter = 0
        # async pipeline: every runner always has a sample() in flight
        self._inflight: Dict[Any, int] = {}
        self._np = np

    # subclass hook: APPO swaps in the clipped-surrogate learner while
    # keeping the whole async actor-learner machinery
    _make_update = staticmethod(make_impala_update)

    def _host_params(self):
        return jax.tree.map(self._np.asarray, self.params)

    def _submit(self, runner_idx: int):
        self._seed_counter += 1
        ref = self.env_runners[runner_idx].sample.remote(
            self._host_params(), self.config.seed + self._seed_counter * 97
        )
        self._inflight[ref] = runner_idx

    def train(self) -> Dict[str, Any]:
        np = self._np
        ray = self._ray
        if not self._inflight:
            for i in range(len(self.env_runners)):
                self._submit(i)
        episode_returns = []
        metrics = {}
        for _ in range(self.config.updates_per_iteration):
            ready, _ = ray.wait(
                list(self._inflight.keys()), num_returns=1, timeout=120
            )
            ref = ready[0]
            runner_idx = self._inflight.pop(ref)
            rollout = ray.get(ref)
            # learner consumes THIS runner's batch; runner immediately
            # resamples with the post-update weights (async staleness <=
            # one rollout — the IMPALA contract)
            batch = {
                "obs": rollout["obs"].reshape(
                    *rollout["obs"].shape[:2], -1
                ),
                "actions": rollout["actions"],
                "rewards": rollout["rewards"],
                "dones": rollout["dones"],
                "logp_mu": rollout["logp"],
                "final_obs": rollout["final_obs"].reshape(
                    rollout["final_obs"].shape[0], -1
                ),
            }
            self.params, self.opt_state, metrics = self._update(
                self.params, self.opt_state, batch
            )
            self._timesteps += int(batch["actions"].size)
            episode_returns.extend(rollout["episode_returns"].tolist())
            self._submit(runner_idx)
        self.iteration += 1
        result = {
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "episode_return_mean": (
                float(np.mean(episode_returns)) if episode_returns else float("nan")
            ),
            "num_episodes": len(episode_returns),
        }
        result.update({k: float(v) for k, v in metrics.items()})
        return result

    def compute_single_action(self, obs) -> int:
        logits, _ = forward(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(logits[0]))

    def save(self, checkpoint_dir: str) -> str:
        import os
        import pickle

        os.makedirs(checkpoint_dir, exist_ok=True)
        state = {
            "params": jax.tree.map(self._np.asarray, self.params),
            "opt_state": jax.tree.map(self._np.asarray, self.opt_state),
            "iteration": self.iteration,
            "timesteps": self._timesteps,
        }
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]

    def stop(self) -> None:
        self._inflight.clear()
        for r in self.env_runners:
            try:
                self._ray.kill(r)
            except Exception:
                pass
        self.env_runners = []
