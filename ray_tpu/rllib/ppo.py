"""PPO: config + jitted learner.

Parity: python/ray/rllib/algorithms/ppo/ (PPOConfig/PPO) +
core/learner/learner.py:107. TPU-native difference (§2.5): the
reference's multi-learner gradient sync is torch DDP
(torch_learner.py:533); here the WHOLE update — GAE, minibatch
epochs, clipped surrogate, optimizer — is one jitted program, and
multi-chip data parallelism is the mesh's data axis (GSPMD psum), not a
wrapper class.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from .core import MLPSpec, forward


@dataclass
class PPOConfig:
    """Builder (reference: algorithm_config.py fluent API)."""

    env: Optional[Union[str, Callable]] = None
    num_env_runners: int = 2
    num_envs_per_env_runner: int = 2
    rollout_fragment_length: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 128
    grad_clip: float = 0.5
    hiddens: Tuple[int, ...] = (64, 64)
    seed: int = 0
    # multi-agent (reference: algorithm_config.multi_agent()):
    # policies = module ids; policy_mapping_fn(agent_id, episode) -> id
    policies: Optional[set] = None
    policy_mapping_fn: Optional[Callable] = None
    env_config: Optional[dict] = None
    # ConnectorV2 pipelines (reference: config.env_to_module_connector /
    # learner connector): builders called per module on the runner
    env_to_module_connector: Optional[Callable] = None
    learner_connector: Optional[Callable] = None

    # -- fluent builder (reference parity) --
    def environment(self, env, *, env_config=None) -> "PPOConfig":
        self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None) -> "PPOConfig":
        """Enable multi-agent training (reference:
        algorithm_config.py multi_agent): `policies` is the set of
        module ids, `policy_mapping_fn(agent_id, episode)` routes each
        agent to one of them."""
        if policies is not None:
            self.policies = set(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def env_runners(self, *, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None,
                    env_to_module_connector=None) -> "PPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        return self

    def training(self, **kwargs) -> "PPOConfig":
        for k, v in kwargs.items():
            if k == "lambda":
                k = "lambda_"
            if not hasattr(self, k):
                raise ValueError(f"unknown PPO training param {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed=None) -> "PPOConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build_algo(self):
        if self.policies or self.policy_mapping_fn:
            from .multi_agent import MultiAgentAlgorithm

            return MultiAgentAlgorithm(self)
        from .algorithm import Algorithm

        return Algorithm(self)

    build = build_algo  # older API alias


def compute_gae(rewards, values, dones, final_value, gamma, lam):
    """Time-major GAE (T, N). Returns (advantages, value_targets)."""
    T = rewards.shape[0]

    def step(carry, xs):
        adv_next, v_next = carry
        r, v, d = xs
        nonterminal = 1.0 - d
        delta = r + gamma * v_next * nonterminal - v
        adv = delta + gamma * lam * nonterminal * adv_next
        return (adv, v), adv

    (_, _), advs = jax.lax.scan(
        step,
        (jnp.zeros_like(final_value), final_value),
        (rewards, values, dones),
        reverse=True,
    )
    return advs, advs + values


_UPDATE_CACHE: dict = {}


def make_ppo_update(config: PPOConfig, spec: MLPSpec):
    """Build (optimizer, jitted update): GAE + epochs × minibatches of
    clipped-surrogate SGD. Everything static-shaped for XLA.

    Builds the optimizer itself (from config.lr/grad_clip) so the cache
    key fully determines the returned closure. Cached per (hyperparams,
    spec) so repeated Algorithm builds in one process (e.g. a test
    suite, or Tune trials) reuse the compiled executable instead of
    retracing."""
    import optax

    cache_key = (
        config.lr, config.gamma, config.lambda_, config.clip_param,
        config.vf_loss_coeff, config.entropy_coeff, config.num_epochs,
        config.minibatch_size, config.grad_clip, spec,
    )
    cached = _UPDATE_CACHE.get(cache_key)
    if cached is not None:
        return cached

    optimizer = optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.adam(config.lr),
    )

    def loss_fn(params, batch):
        logits, values = forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=-1
        )[:, 0]
        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - config.clip_param, 1 + config.clip_param) * adv,
        )
        pi_loss = -jnp.mean(surr)
        vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = (
            pi_loss
            + config.vf_loss_coeff * vf_loss
            - config.entropy_coeff * entropy
        )
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    @jax.jit
    def update(params, opt_state, rollout, rng):
        # rollout: time-major (T, N, ...) from the env runners
        final_value = forward(params, rollout["final_obs"])[1]
        advs, vtarg = compute_gae(
            rollout["rewards"],
            rollout["values"],
            rollout["dones"],
            final_value,
            config.gamma,
            config.lambda_,
        )
        flat = {
            "obs": rollout["obs"].reshape(-1, spec.obs_dim),
            "actions": rollout["actions"].reshape(-1),
            "logp_old": rollout["logp"].reshape(-1),
            "advantages": advs.reshape(-1),
            "value_targets": vtarg.reshape(-1),
        }
        B = flat["actions"].shape[0]
        flat["advantages"] = (
            flat["advantages"] - flat["advantages"].mean()
        ) / (flat["advantages"].std() + 1e-8)
        mb = min(config.minibatch_size, B)
        n_mb = B // mb

        def epoch(carry, key):
            params, opt_state = carry
            perm = jax.random.permutation(key, B)

            def minibatch(carry, idx):
                params, opt_state = carry
                mb_idx = jax.lax.dynamic_slice_in_dim(perm, idx * mb, mb)
                batch = {k: v[mb_idx] for k, v in flat.items()}
                (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, batch
                )
                updates, opt_state = optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), metrics

            (params, opt_state), metrics = jax.lax.scan(
                minibatch, (params, opt_state), jnp.arange(n_mb)
            )
            return (params, opt_state), metrics

        keys = jax.random.split(rng, config.num_epochs)
        (params, opt_state), metrics = jax.lax.scan(
            epoch, (params, opt_state), keys
        )
        metrics = jax.tree.map(lambda m: m.mean(), metrics)
        return params, opt_state, metrics

    _UPDATE_CACHE[cache_key] = (optimizer, update)
    return optimizer, update
