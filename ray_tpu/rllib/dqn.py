"""DQN: off-policy Q-learning with replay + target network.

Parity: python/ray/rllib/algorithms/dqn/ (double-DQN defaults) —
EnvRunner actors collect epsilon-greedy transitions into a ReplayBuffer;
the jitted learner does double-Q targets against a periodically-synced
target network. TPU-native: the whole minibatch update (target calc,
Huber loss, Adam step) is one compiled program; the buffer stays in host
numpy (random access) and only sampled minibatches hit the device.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .core import MLPSpec
from .replay_buffers import ReplayBuffer


@dataclass
class DQNConfig:
    env: Optional[Union[str, Callable]] = None
    num_env_runners: int = 1
    num_envs_per_env_runner: int = 2
    rollout_fragment_length: int = 32
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_capacity: int = 50_000
    train_batch_size: int = 64
    num_steps_sampled_before_learning_starts: int = 500
    target_network_update_freq: int = 200  # learner updates between syncs
    updates_per_iteration: int = 32  # sample rounds per train()
    train_intensity: int = 8  # gradient updates per sample round (the
    # replay ratio lever; reference: training_intensity)
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_steps: int = 4000
    double_q: bool = True
    grad_clip: float = 10.0
    hiddens: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def environment(self, env) -> "DQNConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None) -> "DQNConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "DQNConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DQN training param {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed=None) -> "DQNConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build_algo(self) -> "DQN":
        return DQN(self)

    build = build_algo


def _init_q_net(rng, spec: MLPSpec):
    import math

    def dense(key, fan_in, fan_out, gain):
        w = jax.nn.initializers.orthogonal(gain)(key, (fan_in, fan_out))
        return {"w": w, "b": jnp.zeros((fan_out,))}

    keys = jax.random.split(rng, len(spec.hiddens) + 1)
    layers = []
    fan_in = spec.obs_dim
    for i, h in enumerate(spec.hiddens):
        layers.append(dense(keys[i], fan_in, h, math.sqrt(2.0)))
        fan_in = h
    return {"torso": layers, "head": dense(keys[-1], fan_in, spec.num_actions, 1.0)}


def _q_values(params, obs):
    x = obs
    for layer in params["torso"]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    return x @ params["head"]["w"] + params["head"]["b"]


def double_q_target(params, target_params, batch, *, gamma: float,
                    double_q: bool = True):
    """Bellman target shared by DQN and CQL: online net selects, target
    net evaluates (or plain max), stop-gradient applied."""
    q_next_target = _q_values(target_params, batch["next_obs"])
    if double_q:
        next_a = jnp.argmax(_q_values(params, batch["next_obs"]), axis=1)
        q_next = jnp.take_along_axis(
            q_next_target, next_a[:, None], axis=1
        )[:, 0]
    else:
        q_next = jnp.max(q_next_target, axis=1)
    return batch["rewards"] + gamma * (1.0 - batch["dones"]) * (
        jax.lax.stop_gradient(q_next)
    )


_UPDATE_CACHE: dict = {}


def make_dqn_update(config: DQNConfig, spec: MLPSpec):
    import optax

    key = (config.lr, config.gamma, config.double_q, config.grad_clip, spec)
    cached = _UPDATE_CACHE.get(key)
    if cached is not None:
        return cached
    optimizer = optax.chain(
        optax.clip_by_global_norm(config.grad_clip), optax.adam(config.lr)
    )

    def loss_fn(params, target_params, batch):
        q = _q_values(params, batch["obs"])
        q_taken = jnp.take_along_axis(q, batch["actions"][:, None], axis=1)[:, 0]
        target = double_q_target(
            params, target_params, batch,
            gamma=config.gamma, double_q=config.double_q,
        )
        td = q_taken - target
        return jnp.mean(optax.huber_loss(td)), td

    @jax.jit
    def update(params, target_params, opt_state, batch):
        (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, target_params, batch
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, td

    _UPDATE_CACHE[key] = (optimizer, update)
    return optimizer, update


class _EpsilonGreedyRunner:
    """Rollout actor: epsilon-greedy transitions as flat (s,a,r,s',d)
    arrays (reference: EnvRunner with an EpsilonGreedy exploration)."""

    def __init__(self, env_creator, num_envs, seed, fragment):
        import gymnasium as gym

        if isinstance(env_creator, str):
            env_id = env_creator
            fns = [lambda: gym.make(env_id) for _ in range(num_envs)]
        else:
            fns = [env_creator for _ in range(num_envs)]
        self.envs = gym.vector.SyncVectorEnv(
            fns, autoreset_mode=gym.vector.AutoresetMode.SAME_STEP
        )
        self.num_envs = num_envs
        self.fragment = fragment
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.envs.reset(seed=seed)
        self._ep_returns = np.zeros(num_envs)
        self.completed: deque = deque(maxlen=100)  # trailing window (GL005)

    def obs_space_dim(self):
        return int(np.prod(self.envs.single_observation_space.shape))

    def num_actions(self):
        return int(self.envs.single_action_space.n)

    def sample(self, params, epsilon: float):
        T, N = self.fragment, self.num_envs
        obs_dim = self.obs_space_dim()
        out = {
            "obs": np.zeros((T * N, obs_dim), np.float32),
            "actions": np.zeros((T * N,), np.int64),
            "rewards": np.zeros((T * N,), np.float32),
            "next_obs": np.zeros((T * N, obs_dim), np.float32),
            "dones": np.zeros((T * N,), np.float32),
        }
        obs = self.obs
        for t in range(T):
            q = np.asarray(_q_values(params, jnp.asarray(obs, jnp.float32)))
            greedy = q.argmax(axis=1)
            rand = self.rng.integers(0, q.shape[1], size=N)
            explore = self.rng.random(N) < epsilon
            actions = np.where(explore, rand, greedy)
            next_obs, rewards, term, trunc, infos = self.envs.step(actions)
            # time-limit truncation is not termination for bootstrapping
            done_for_target = np.asarray(term, np.float32)
            from .env_runner import substitute_final_obs

            next_store = substitute_final_obs(next_obs, term, trunc, infos)
            sl = slice(t * N, (t + 1) * N)
            out["obs"][sl] = obs.reshape(N, -1)
            out["actions"][sl] = actions
            out["rewards"][sl] = rewards
            out["next_obs"][sl] = next_store.reshape(N, -1)
            out["dones"][sl] = done_for_target
            self._ep_returns += rewards
            for i in np.nonzero(np.logical_or(term, trunc))[0]:
                self.completed.append(float(self._ep_returns[i]))
                self._ep_returns[i] = 0.0
            obs = next_obs
        self.obs = obs
        out["episode_returns"] = np.asarray(list(self.completed), np.float32)
        return out


class DQN:
    def __init__(self, config: DQNConfig):
        import ray_tpu

        if config.env is None:
            raise ValueError("config.environment(env) is required")
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.config = config
        self._ray = ray_tpu
        runner_cls = ray_tpu.remote(_EpsilonGreedyRunner)
        self.env_runners = [
            runner_cls.remote(
                config.env, config.num_envs_per_env_runner,
                config.seed + 1000 * i, config.rollout_fragment_length,
            )
            for i in range(config.num_env_runners)
        ]
        obs_dim = ray_tpu.get(self.env_runners[0].obs_space_dim.remote())
        num_actions = ray_tpu.get(self.env_runners[0].num_actions.remote())
        self.spec = MLPSpec(obs_dim, num_actions, tuple(config.hiddens))
        self.params = _init_q_net(jax.random.PRNGKey(config.seed), self.spec)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.optimizer, self._update = make_dqn_update(config, self.spec)
        self.opt_state = self.optimizer.init(self.params)
        self.buffer = ReplayBuffer(config.buffer_capacity, seed=config.seed)
        self.iteration = 0
        self._timesteps = 0
        self._updates = 0

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._timesteps / max(1, c.epsilon_decay_steps))
        return c.epsilon_start + frac * (c.epsilon_end - c.epsilon_start)

    def train(self) -> Dict[str, Any]:
        ray = self._ray
        c = self.config
        host_params = jax.tree.map(np.asarray, self.params)
        # per-runner latest last-100 window (cumulative per runner):
        # keep the newest per runner, concat across runners
        latest_windows: Dict[int, list] = {}
        loss_val = float("nan")
        for _ in range(c.updates_per_iteration):
            rollouts = ray.get([
                r.sample.remote(host_params, self._epsilon())
                for r in self.env_runners
            ])
            for idx, ro in enumerate(rollouts):
                latest_windows[idx] = ro.pop("episode_returns").tolist()
                self.buffer.add(ro)
                self._timesteps += len(ro["actions"])
            if (
                self._timesteps < c.num_steps_sampled_before_learning_starts
                or len(self.buffer) < c.train_batch_size
            ):
                continue
            for _ in range(c.train_intensity):
                batch = self.buffer.sample(c.train_batch_size)
                self.params, self.opt_state, loss, _ = self._update(
                    self.params, self.target_params, self.opt_state, batch
                )
                loss_val = float(loss)
                self._updates += 1
                if self._updates % c.target_network_update_freq == 0:
                    self.target_params = jax.tree.map(lambda x: x, self.params)
            host_params = jax.tree.map(np.asarray, self.params)
        from .env_runner import merge_return_windows

        episode_returns = merge_return_windows(latest_windows)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "episode_return_mean": (
                float(np.mean(episode_returns)) if episode_returns
                else float("nan")
            ),
            "num_episodes": len(episode_returns),
            "epsilon": self._epsilon(),
            "loss": loss_val,
            "buffer_size": len(self.buffer),
        }

    def compute_single_action(self, obs) -> int:
        q = _q_values(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(q[0]))

    def stop(self) -> None:
        for r in self.env_runners:
            try:
                self._ray.kill(r)
            except Exception:
                pass
        self.env_runners = []
