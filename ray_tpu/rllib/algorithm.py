"""Algorithm: the RL training driver.

Parity: python/ray/rllib/algorithms/algorithm.py (training_step :2038):
each train() iteration fans rollout collection out to the EnvRunner
actors, runs the jitted learner update on the concatenated batch, and
broadcasts fresh weights. Checkpointable (save/restore of params +
optimizer state), mirroring the reference's Checkpointable mixin.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

import numpy as np


class Algorithm:
    def __init__(self, config):
        import jax

        import ray_tpu

        from .core import MLPSpec, init_mlp_module
        from .env_runner import SingleAgentEnvRunner
        from .ppo import make_ppo_update

        if config.env is None:
            raise ValueError("config.environment(env) is required")
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.config = config

        env = config.env
        env_config = getattr(config, "env_config", None)
        if env_config and callable(env) and not isinstance(env, str):
            # close the env_config over the creator — the runner calls
            # creators with no arguments
            creator, cfg = env, dict(env_config)
            env = lambda: creator(cfg)  # noqa: E731
        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        self.env_runners = [
            runner_cls.remote(
                env,
                config.num_envs_per_env_runner,
                config.seed + 1000 * i,
                config.rollout_fragment_length,
                config.gamma,
            )
            for i in range(config.num_env_runners)
        ]
        obs_dim = ray_tpu.get(self.env_runners[0].obs_space_dim.remote())
        num_actions = ray_tpu.get(self.env_runners[0].num_actions.remote())
        self.spec = MLPSpec(obs_dim, num_actions, tuple(config.hiddens))
        self.params = init_mlp_module(jax.random.PRNGKey(config.seed), self.spec)
        self.optimizer, self._update = make_ppo_update(config, self.spec)
        self.opt_state = self.optimizer.init(self.params)
        self._rng = jax.random.PRNGKey(config.seed + 1)
        self.iteration = 0
        self._timesteps = 0

    # ------------------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: Algorithm.train)."""
        import jax
        import ray_tpu

        host_params = jax.tree.map(np.asarray, self.params)
        rollouts = ray_tpu.get(
            [
                r.sample.remote(host_params, self.config.seed + self.iteration * 97 + i)
                for i, r in enumerate(self.env_runners)
            ]
        )
        # concat across runners on the env axis (time-major T, N)
        batch = {
            k: np.concatenate([ro[k] for ro in rollouts], axis=1)
            for k in ("obs", "actions", "rewards", "dones", "logp", "values")
        }
        batch["obs"] = batch["obs"].reshape(
            batch["obs"].shape[0], batch["obs"].shape[1], -1
        )
        batch["final_obs"] = np.concatenate(
            [ro["final_obs"].reshape(ro["final_obs"].shape[0], -1) for ro in rollouts],
            axis=0,
        )
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch, sub
        )
        self.iteration += 1
        self._timesteps += int(batch["actions"].size)
        ep_returns = np.concatenate(
            [ro["episode_returns"] for ro in rollouts]
        )
        # lag-free learning signal: only the episodes that finished
        # during this iteration's fragments (episode_return_mean is a
        # trailing-100 window that doubles as a lifetime mean early on)
        recent = np.concatenate(
            [
                ro.get("episode_returns_recent", np.zeros(0, np.float32))
                for ro in rollouts
            ]
        )
        result = {
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "episode_return_mean": float(ep_returns.mean()) if len(ep_returns) else float("nan"),
            "num_episodes": int(len(ep_returns)),
            "episode_return_recent_mean": (
                float(recent.mean()) if len(recent) else float("nan")
            ),
            "num_episodes_recent": int(len(recent)),
        }
        result.update({k: float(v) for k, v in metrics.items()})
        return result

    # ------------------------------------------------------------------
    def compute_single_action(self, obs) -> int:
        import jax.numpy as jnp

        from .core import forward

        logits, _ = forward(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(logits[0]))

    def save(self, checkpoint_dir: str) -> str:
        import jax

        os.makedirs(checkpoint_dir, exist_ok=True)
        state = {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "iteration": self.iteration,
            "timesteps": self._timesteps,
        }
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]

    def stop(self) -> None:
        import ray_tpu

        for r in self.env_runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.env_runners = []
