"""Podracer architectures (Hessel et al. 2021): Anakin & Sebulba.

Two TPU-native RL layouts behind one ``PodracerConfig``:

- **Anakin** — environment step and learner update co-jitted into one
  on-chip program (``jax.lax.scan`` over vectorized pure-JAX envs, SPMD
  over ``parallel/mesh.py``), driven by a compiled-DAG resident exec
  loop so the host never re-dispatches per step.
- **Sebulba** — actor workers and a learner gang-placed on separate
  slices; trajectory hand-off rides ``fn.map`` bulk submission and the
  direct object plane (rollout batches never relay through the hub),
  the learner all-reduces gradients over a cached jitted collective
  group, and parameters broadcast back on a version-tagged KV channel.

Both run end to end on CPU (``JAX_PLATFORMS=cpu``); the MULTICHIP
harness path is stubbed until the live-TPU tunnel returns.
"""

from .config import PodracerConfig
from .jax_env import JaxCartPole, get_jax_env, register_jax_env
from .anakin import AnakinDriver
from .sebulba import SebulbaDriver

__all__ = [
    "PodracerConfig",
    "JaxCartPole",
    "get_jax_env",
    "register_jax_env",
    "AnakinDriver",
    "SebulbaDriver",
]
