"""Pure-JAX vectorized environments for the Podracer loops.

Anakin needs the environment step INSIDE the jitted program (the whole
point of the architecture: env + learner fused into one XLA
executable), so gymnasium's process-bound envs can't be used there.
This module provides jit-compatible env dynamics with the same
observation/action contract as ``env_runner.SingleAgentEnvRunner`` —
an env is a class of pure functions over an explicit state pytree:

    reset(key)              -> (state, obs)
    step(state, action, key) -> (state, obs, reward, done)

Auto-reset is folded into ``step`` (SAME_STEP semantics, mirroring the
gymnasium vector path): when an episode ends, ``done=1`` is returned
together with the freshly-reset observation, so a ``lax.scan`` over
steps never leaves the program. The bootstrap value of a reset obs is
masked by ``1 - done`` inside V-trace, so the swap is sound.
"""

from __future__ import annotations

from typing import Dict, Type

import jax
import jax.numpy as jnp


class JaxCartPole:
    """CartPole-v1 dynamics (Barto, Sutton & Anderson 1983) as pure
    JAX — numerically the same Euler integration and thresholds as
    ``gymnasium/envs/classic_control/cartpole.py``, including the
    500-step time limit (treated as ``done``)."""

    obs_dim = 4
    num_actions = 2
    max_steps = 500

    _GRAVITY = 9.8
    _MASSCART = 1.0
    _MASSPOLE = 0.1
    _TOTAL_MASS = _MASSPOLE + _MASSCART
    _LENGTH = 0.5  # half the pole's length
    _POLEMASS_LENGTH = _MASSPOLE * _LENGTH
    _FORCE_MAG = 10.0
    _TAU = 0.02
    _THETA_THRESHOLD = 12 * 2 * jnp.pi / 360
    _X_THRESHOLD = 2.4

    @classmethod
    def reset(cls, key):
        phys = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        state = {"phys": phys, "t": jnp.zeros((), jnp.int32)}
        return state, phys.astype(jnp.float32)

    @classmethod
    def step(cls, state, action, key):
        x, x_dot, theta, theta_dot = state["phys"]
        force = jnp.where(action == 1, cls._FORCE_MAG, -cls._FORCE_MAG)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        temp = (
            force + cls._POLEMASS_LENGTH * theta_dot**2 * sintheta
        ) / cls._TOTAL_MASS
        thetaacc = (cls._GRAVITY * sintheta - costheta * temp) / (
            cls._LENGTH
            * (4.0 / 3.0 - cls._MASSPOLE * costheta**2 / cls._TOTAL_MASS)
        )
        xacc = temp - cls._POLEMASS_LENGTH * thetaacc * costheta / cls._TOTAL_MASS
        x = x + cls._TAU * x_dot
        x_dot = x_dot + cls._TAU * xacc
        theta = theta + cls._TAU * theta_dot
        theta_dot = theta_dot + cls._TAU * thetaacc
        phys = jnp.stack([x, x_dot, theta, theta_dot])
        t = state["t"] + 1

        terminated = (
            (jnp.abs(x) > cls._X_THRESHOLD)
            | (jnp.abs(theta) > cls._THETA_THRESHOLD)
        )
        done = terminated | (t >= cls.max_steps)
        reward = jnp.float32(1.0)

        # SAME_STEP auto-reset: the returned obs after a done step is
        # the next episode's first obs; V-trace masks its bootstrap.
        reset_state, reset_obs = cls.reset(key)
        next_state = {
            "phys": jnp.where(done, reset_state["phys"], phys),
            "t": jnp.where(done, reset_state["t"], t),
        }
        obs = jnp.where(done, reset_obs, phys).astype(jnp.float32)
        return next_state, obs, reward, done.astype(jnp.float32)


JAX_ENVS: Dict[str, Type] = {"CartPole-v1": JaxCartPole}


def register_jax_env(name: str, env_cls) -> None:
    """Register a jittable env under ``name`` for PodracerConfig.env."""
    JAX_ENVS[name] = env_cls


def get_jax_env(name: str):
    try:
        return JAX_ENVS[name]
    except KeyError:
        raise ValueError(
            f"no pure-JAX env registered for {name!r} (have "
            f"{sorted(JAX_ENVS)}); register one with register_jax_env()"
        ) from None
