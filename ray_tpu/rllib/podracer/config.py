"""PodracerConfig: one fluent builder for both Podracer layouts.

Hyperparameter fields duck-type ``IMPALAConfig``/``APPOConfig`` so the
learner reuses ``make_impala_loss``/``make_appo_loss`` verbatim — the
Podracer subsystem adds topology, not a new RL algorithm.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..core import MLPSpec
from .jax_env import get_jax_env

MODES = ("anakin", "sebulba")
LOSSES = ("vtrace", "appo")


@dataclass
class PodracerConfig:
    mode: str = "anakin"
    env: str = "CartPole-v1"

    # -- learner hyperparams (IMPALA/APPO duck-type surface) ----------
    lr: float = 5e-3
    gamma: float = 0.99
    vtrace_clip_rho: float = 1.0
    vtrace_clip_c: float = 1.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 1.0
    clip_param: float = 0.3  # read only when loss == "appo"
    loss: str = "vtrace"
    hiddens: Tuple[int, ...] = (32, 32)
    seed: int = 0
    rollout_fragment_length: int = 16  # T: env steps per superstep/fragment

    # -- anakin topology ----------------------------------------------
    num_envs: int = 64  # total vectorized envs, sharded over the mesh
    anakin_num_devices: Optional[int] = None  # None -> every local device
    anakin_supersteps_per_call: int = 1  # supersteps per resident-loop tick
    use_compiled_dag: bool = True  # False: plain actor calls (debug path)

    # -- sebulba topology ---------------------------------------------
    num_actors: int = 2
    envs_per_actor: int = 16
    learner_shards: int = 1  # devices in the learner collective group
    num_sgd_steps: int = 1  # learner SGD passes over each round's batch
    param_sync_interval: int = 1  # publish params every k learner steps
    max_inflight_rounds: int = 2  # actor rounds racing ahead of the learner
    placement_strategy: Optional[str] = None  # None -> SLICE on TPU, PACK on CPU
    namespace: str = "default"  # isolates the version-tagged param channel

    # -- fluent builders (rllib AlgorithmConfig idiom) ----------------
    def environment(self, env: str) -> "PodracerConfig":
        self.env = env
        return self

    def podracer(self, *, mode=None, num_envs=None, anakin_num_devices=None,
                 anakin_supersteps_per_call=None, use_compiled_dag=None,
                 learner_shards=None, param_sync_interval=None,
                 max_inflight_rounds=None, num_sgd_steps=None,
                 placement_strategy=None, namespace=None) -> "PodracerConfig":
        for k, v in (
            ("mode", mode), ("num_envs", num_envs),
            ("anakin_num_devices", anakin_num_devices),
            ("anakin_supersteps_per_call", anakin_supersteps_per_call),
            ("use_compiled_dag", use_compiled_dag),
            ("learner_shards", learner_shards),
            ("param_sync_interval", param_sync_interval),
            ("max_inflight_rounds", max_inflight_rounds),
            ("num_sgd_steps", num_sgd_steps),
            ("placement_strategy", placement_strategy),
            ("namespace", namespace),
        ):
            if v is not None:
                setattr(self, k, v)
        return self

    def env_runners(self, *, num_actors=None, envs_per_actor=None,
                    rollout_fragment_length=None) -> "PodracerConfig":
        if num_actors is not None:
            self.num_actors = num_actors
        if envs_per_actor is not None:
            self.envs_per_actor = envs_per_actor
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "PodracerConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown Podracer training param {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed=None) -> "PodracerConfig":
        if seed is not None:
            self.seed = seed
        return self

    # -- derived ------------------------------------------------------
    @property
    def env_cls(self):
        return get_jax_env(self.env)

    @property
    def spec(self) -> MLPSpec:
        env_cls = self.env_cls
        return MLPSpec(
            obs_dim=env_cls.obs_dim,
            num_actions=env_cls.num_actions,
            hiddens=tuple(self.hiddens),
        )

    def validate(self) -> None:
        if self.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {self.mode!r}")
        if self.loss not in LOSSES:
            raise ValueError(f"loss must be one of {LOSSES}, got {self.loss!r}")
        if self.mode == "sebulba":
            total = self.num_actors * self.envs_per_actor
            if total % max(1, self.learner_shards) != 0:
                raise ValueError(
                    f"num_actors*envs_per_actor ({total}) must divide evenly "
                    f"over learner_shards ({self.learner_shards})"
                )
        self.env_cls  # raises on unknown env

    def build(self):
        """Instantiate the driver for the selected mode."""
        self.validate()
        if self.mode == "anakin":
            from .anakin import AnakinDriver

            return AnakinDriver(self)
        from .sebulba import SebulbaDriver

        return SebulbaDriver(self)

    build_algo = build
