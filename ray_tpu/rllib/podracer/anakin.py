"""Anakin: env step + learner update co-jitted into one on-chip loop.

The architecture from Hessel et al. 2021 §3.1: vectorized pure-JAX
envs and the SGD update fuse into a single XLA program (a
``lax.scan`` over env steps feeding straight into the gradient step),
SPMD over the ``parallel/mesh.py`` device mesh — env state shards over
the batch axes, params replicate, and the partitioner inserts the
gradient all-reduce. The driver never re-dispatches per step: a
compiled-DAG resident exec loop parks on the worker, and each host
"tick" is pure shm-channel I/O (one command array in, one metrics
array out) covering ``anakin_supersteps_per_call`` fused supersteps.

Determinism: the whole tick stream is a pure function of
``config.seed`` (per-superstep keys are ``fold_in(seed_key, k)``), so
a same-seed run reproduces the reward trajectory bitwise on CPU.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...util import tracing
from .learner import make_acting_fns, make_update_fn

CMD_DIM = 2  # [tick_index, reserved]
METRICS_DIM = 10
# metrics vector layout (float32):
#   0 ticks_done      1 updates_total    2 env_steps_total
#   3 ep_return_sum   4 ep_return_count  (cumulative completed episodes)
#   5 policy_loss     6 vf_loss          7 entropy   (last superstep)
#   8 ep_return_sum_tick  9 ep_return_count_tick  (this tick only)


class AnakinWorker:
    """The single resident actor: owns the mesh, the carry (params,
    opt_state, env state) and the fused superstep program."""

    def __init__(self, config_blob: bytes):
        import jax

        from ...parallel.mesh import (
            batch_sharding,
            dp_degree,
            make_mesh,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        config = pickle.loads(config_blob)
        self.config = config
        devs = jax.devices()
        if config.anakin_num_devices:
            devs = devs[: config.anakin_num_devices]
        self.mesh = make_mesh(devices=devs)
        dp = dp_degree(self.mesh)
        if config.num_envs % dp != 0:
            raise ValueError(
                f"num_envs ({config.num_envs}) must divide over the "
                f"mesh's data-parallel degree ({dp})"
            )
        spec = config.spec
        env_cls = config.env_cls
        init_envs, act = make_acting_fns(env_cls, config.rollout_fragment_length)
        _, update = make_update_fn(config, spec)

        def superstep(carry, key):
            params, opt_state, env_state, obs, ep_ret = carry
            env_state, obs, ep_ret, batch, ep_sum, ep_n = act(
                params, env_state, obs, ep_ret, key
            )
            params, opt_state, metrics = update(params, opt_state, batch)
            stats = (
                ep_sum, ep_n,
                metrics["policy_loss"], metrics["vf_loss"],
                metrics["entropy"],
            )
            return (params, opt_state, env_state, obs, ep_ret), stats

        # -- build the carry with explicit SPMD placement -------------
        from ..core import init_mlp_module

        base = jax.random.PRNGKey(config.seed)
        k_model, k_env, self._key = jax.random.split(base, 3)
        params = init_mlp_module(k_model, spec)
        optimizer, _ = make_update_fn(config, spec)
        opt_state = optimizer.init(params)
        env_state, obs, ep_ret = jax.jit(
            init_envs, static_argnums=1
        )(k_env, config.num_envs)

        repl = NamedSharding(self.mesh, P())
        params = jax.device_put(params, repl)
        opt_state = jax.device_put(opt_state, repl)
        env_state = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, batch_sharding(self.mesh, x.ndim - 1)),
            env_state,
        )
        obs = jax.device_put(obs, batch_sharding(self.mesh, obs.ndim - 1))
        ep_ret = jax.device_put(ep_ret, batch_sharding(self.mesh, 0))
        self._carry = (params, opt_state, env_state, obs, ep_ret)

        # AOT-compile against the real carry so the resident loop's
        # first tick never pays the trace+lower cost, and so the split
        # trace-mode programs share placement with the fused one.
        key0 = jax.device_put(jax.random.fold_in(self._key, 0), repl)
        self._superstep = (
            jax.jit(superstep).lower(self._carry, key0).compile()
        )
        self._act = (
            jax.jit(act)
            .lower(params, env_state, obs, ep_ret, key0)
            .compile()
        )
        self._update = None  # lazily compiled on first traced tick
        self._update_fn = update
        self._jax = jax
        self._repl = repl
        self._supersteps = 0
        self._ticks = 0
        self._ep_sum = 0.0
        self._ep_n = 0.0
        self._last_losses = (0.0, 0.0, 0.0)
        self._steps_per_superstep = (
            config.rollout_fragment_length * config.num_envs
        )

    def ready(self) -> bool:
        return True

    def _next_key(self):
        key = self._jax.random.fold_in(self._key, self._supersteps)
        return self._jax.device_put(key, self._repl)

    def _fold_stats(self, stats):
        ep_sum, ep_n, pi_l, vf_l, ent = (float(s) for s in stats)
        self._last_losses = (pi_l, vf_l, ent)
        return ep_sum, ep_n

    def _tick_fused(self, n: int):
        tick_sum = tick_n = 0.0
        for _ in range(n):
            self._carry, stats = self._superstep(self._carry, self._next_key())
            self._supersteps += 1
            s, c = self._fold_stats(stats)
            tick_sum += s
            tick_n += c
        return tick_sum, tick_n

    def _tick_traced(self, n: int):
        """Trace mode: the acting scan and the update run as two jitted
        programs so each gets its own span — the fused program can't
        be split from the outside. Slower than fused; only taken when
        tracing is live."""
        jax = self._jax
        tick_sum = tick_n = 0.0
        for _ in range(n):
            params, opt_state, env_state, obs, ep_ret = self._carry
            with tracing.span(
                "podracer.env_step", stage="podracer.env_step", mode="anakin"
            ):
                env_state, obs, ep_ret, batch, ep_sum, ep_n = self._act(
                    params, env_state, obs, ep_ret, self._next_key()
                )
                jax.block_until_ready(batch)
            with tracing.span(
                "podracer.learner_update",
                stage="podracer.learner_update",
                mode="anakin",
            ):
                if self._update is None:
                    self._update = (
                        jax.jit(self._update_fn)
                        .lower(params, opt_state, batch)
                        .compile()
                    )
                params, opt_state, metrics = self._update(
                    params, opt_state, batch
                )
                jax.block_until_ready(params)
            self._carry = (params, opt_state, env_state, obs, ep_ret)
            self._supersteps += 1
            s, c = self._fold_stats((
                ep_sum, ep_n,
                metrics["policy_loss"], metrics["vf_loss"],
                metrics["entropy"],
            ))
            tick_sum += s
            tick_n += c
        return tick_sum, tick_n

    def tick(self, cmd: np.ndarray) -> np.ndarray:
        """One resident-loop turn: run ``anakin_supersteps_per_call``
        fused supersteps, return the fixed-shape metrics vector."""
        n = self.config.anakin_supersteps_per_call
        if tracing.is_enabled():
            tick_sum, tick_n = self._tick_traced(n)
        else:
            tick_sum, tick_n = self._tick_fused(n)
        self._ep_sum += tick_sum
        self._ep_n += tick_n
        pi_l, vf_l, ent = self._last_losses
        self._ticks += 1
        return np.array(
            [
                self._ticks,
                self._supersteps,
                self._supersteps * self._steps_per_superstep,
                self._ep_sum,
                self._ep_n,
                pi_l,
                vf_l,
                ent,
                tick_sum,
                tick_n,
            ],
            dtype=np.float32,
        )


class AnakinDriver:
    """Drives the resident AnakinWorker through a channel-compiled DAG:
    ``train(n)`` is n shm ring-buffer round trips, zero scheduler
    round trips after compile."""

    def __init__(self, config):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self._ray = ray_tpu
        self.config = config
        blob = pickle.dumps(config)
        worker_cls = ray_tpu.remote(AnakinWorker)
        self._worker = worker_cls.remote(blob)
        ray_tpu.get(self._worker.ready.remote(), timeout=300)
        self._compiled = None
        if config.use_compiled_dag:
            from ...dag import InputNode

            with InputNode() as inp:
                dag = self._worker.tick.bind(
                    inp.with_shm_channel((CMD_DIM,), "float32")
                ).with_shm_channel((METRICS_DIM,), "float32")
            self._compiled = dag.experimental_compile(
                max_inflight_executions=2
            )
        self._tick_idx = 0
        self._env_steps_seen = 0.0

    def _tick(self, timeout: float = 300.0) -> np.ndarray:
        cmd = np.array([self._tick_idx, 0], dtype=np.float32)
        self._tick_idx += 1
        if self._compiled is not None:
            return self._compiled.execute(cmd).get(timeout=timeout)
        return self._ray.get(self._worker.tick.remote(cmd), timeout=timeout)

    def train(self, num_ticks: int) -> Dict[str, Any]:
        """Run ``num_ticks`` resident-loop turns; returns aggregate
        throughput plus the per-tick reward trajectory (bitwise
        reproducible for a given seed on CPU)."""
        rows: List[np.ndarray] = []
        t0 = time.perf_counter()
        for _ in range(num_ticks):
            rows.append(self._tick())
        elapsed = time.perf_counter() - t0
        last = rows[-1]
        env_steps = float(last[2]) - self._env_steps_seen
        self._env_steps_seen = float(last[2])
        trajectory = [
            (float(r[8] / r[9]) if r[9] > 0 else float("nan")) for r in rows
        ]
        return {
            "mode": "anakin",
            "ticks": int(last[0]),
            "updates": int(last[1]),
            "env_steps_total": int(last[2]),
            "env_steps": int(env_steps),
            "time_s": elapsed,
            "steps_per_sec": env_steps / elapsed if elapsed > 0 else 0.0,
            "episode_return_mean": (
                float(last[3] / last[4]) if last[4] > 0 else float("nan")
            ),
            "num_episodes": int(last[4]),
            "policy_loss": float(last[5]),
            "vf_loss": float(last[6]),
            "entropy": float(last[7]),
            "reward_trajectory": trajectory,
            "metrics_rows": np.stack(rows),
        }

    def stop(self) -> None:
        if self._compiled is not None:
            self._compiled.teardown()
            self._compiled = None
        try:
            self._ray.kill(self._worker)
        except Exception:
            pass
