"""Shared jitted programs for both Podracer layouts.

Everything here is a *factory of pure functions*: the acting scan
(vectorized env interaction producing a time-major V-trace batch) and
the SGD update (IMPALA or APPO loss, reused from the existing rllib
algorithms). Anakin inlines both into one fused superstep; Sebulba
jits the acting scan on the actor workers and wraps the update in a
shard_map over the learner collective group's mesh so the gradient
all-reduce rides the cached jitted collective path.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..appo import make_appo_loss
from ..core import MLPSpec, forward
from ..impala import make_impala_loss


def select_loss(config, spec: MLPSpec):
    if config.loss == "appo":
        return make_appo_loss(config, spec)
    return make_impala_loss(config, spec)


def make_optimizer(config):
    import optax

    return optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.adam(config.lr),
    )


def make_acting_fns(env_cls, rollout_len: int):
    """(init_envs, act): the vectorized interaction programs.

    ``init_envs(key, n)`` -> (env_state, obs, ep_ret) for n envs.
    ``act(params, env_state, obs, ep_ret, key)`` scans ``rollout_len``
    steps and returns ``(env_state, obs, ep_ret, batch, ep_sum, ep_n)``
    where ``batch`` is the time-major (T, N) V-trace batch and
    ``ep_sum``/``ep_n`` aggregate episode returns completed during the
    fragment (the lag-free learning-progress signal).
    """
    reset_v = jax.vmap(env_cls.reset)
    step_v = jax.vmap(env_cls.step)

    def init_envs(key, n: int):
        env_state, obs = reset_v(jax.random.split(key, n))
        return env_state, obs, jnp.zeros((n,), jnp.float32)

    def act(params, env_state, obs, ep_ret, key):
        def body(carry, key_t):
            env_state, obs, ep_ret = carry
            logits, _ = forward(params, obs)  # (N, A)
            key_act, key_env = jax.random.split(key_t)
            actions = jax.random.categorical(key_act, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, actions[:, None], 1)[:, 0]
            env_keys = jax.random.split(key_env, actions.shape[0])
            env_state, next_obs, rewards, dones = step_v(
                env_state, actions, env_keys
            )
            ep_ret = ep_ret + rewards
            done_sum = jnp.sum(ep_ret * dones)
            done_n = jnp.sum(dones)
            ep_ret = ep_ret * (1.0 - dones)
            step_out = {
                "obs": obs,
                "actions": actions,
                "rewards": rewards,
                "dones": dones,
                "logp_mu": logp,
            }
            return (env_state, next_obs, ep_ret), (step_out, done_sum, done_n)

        keys = jax.random.split(key, rollout_len)
        (env_state, obs, ep_ret), (batch, done_sums, done_ns) = jax.lax.scan(
            body, (env_state, obs, ep_ret), keys
        )
        batch["final_obs"] = obs  # bootstrap obs; masked by dones in V-trace
        return env_state, obs, ep_ret, batch, jnp.sum(done_sums), jnp.sum(done_ns)

    return init_envs, act


def make_update_fn(config, spec: MLPSpec):
    """(optimizer, update): one un-jitted SGD step over a time-major
    batch — callers jit (Sebulba) or inline into a larger jitted
    program (Anakin's fused superstep)."""
    import optax

    optimizer = make_optimizer(config)
    loss_fn = select_loss(config, spec)

    def update(params, opt_state, batch):
        (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    return optimizer, update


_SHARDED_UPDATE_CACHE: Dict[Tuple, Any] = {}


def make_sharded_update(config, spec: MLPSpec, group):
    """(optimizer, jitted update) with the batch sharded over the
    learner collective ``group`` (util.collective XlaGroup): each shard
    computes grads on its slice of the env axis, the all-reduce is a
    ``psum`` over the group's mesh axis — one cached compiled program
    per (hyperparams, spec, world), exactly the XlaGroup contract.
    """
    from jax.sharding import PartitionSpec as P

    from ...util.collective.collective_group.xla_group import shard_map

    key = (
        config.loss, config.lr, config.gamma, config.vtrace_clip_rho,
        config.vtrace_clip_c, config.vf_loss_coeff, config.entropy_coeff,
        config.grad_clip, config.clip_param, spec, group.world_size,
    )
    cached = _SHARDED_UPDATE_CACHE.get(key)
    if cached is not None:
        return cached

    import optax

    optimizer = make_optimizer(config)
    loss_fn = select_loss(config, spec)
    mesh = group.mesh
    axis = mesh.axis_names[0]  # "group"
    world = group.world_size

    def shard_step(params, opt_state, batch):
        (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch
        )
        # the learner all-reduce: mean local grads over the group axis
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis) / world, grads
        )
        metrics = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(m, axis) / world, metrics
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    # params/opt_state replicated, batch sharded on the env axis (axis
    # 1 of the time-major (T, N) arrays; final_obs is (N, obs_dim) so
    # its env axis is 0)
    batch_specs = {
        k: P(None, axis)
        for k in ("obs", "actions", "rewards", "dones", "logp_mu")
    }
    batch_specs["final_obs"] = P(axis)

    update = jax.jit(
        shard_map(
            shard_step,
            mesh=mesh,
            in_specs=(P(), P(), batch_specs),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )

    _SHARDED_UPDATE_CACHE[key] = (optimizer, update)
    return optimizer, update
