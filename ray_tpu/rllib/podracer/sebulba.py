"""Sebulba: actor/learner split across slices (Hessel et al. 2021 §3.2).

Topology: ``num_actors`` rollout workers and one learner worker,
gang-placed in two placement groups (SLICE strategy when TPU chips are
present, PACK on CPU). Every data-plane hop rides the cheapest path
the runtime has:

- **actor fan-out** — each round's ``sample_fragment`` tasks go out as
  ONE ``fn.map`` SUBMIT_TASKS frame (bulk submission);
- **trajectory hand-off** — each fragment returns a >=100KiB rollout
  batch, which the result path encodes as a shm segment (VAL_SHM):
  only the segment *name* crosses the hub, the learner pulls bytes
  over the direct object plane — zero hub relay for rollout payloads;
- **learner all-reduce** — gradients ``psum`` over a cached jitted
  collective group (``util.collective`` XlaGroup mesh);
- **param broadcast** — the learner publishes ``(version, params)`` on
  a version-tagged KV channel; actors poll it at fragment start and
  cache by version, so a stale learner never wedges the actor loop.

Fault model: the learner update is a *plain task* — a chaos
``worker_kill`` mid-update is survived by lineage retry (same input
state ref + same trajectory refs -> identical recomputed output), so
the step counter resumes monotonically from the last published state
and actors keep sampling against the last KV version throughout.
"""

from __future__ import annotations

import pickle
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ...util import tracing
from ...util.scheduling_strategies import PlacementGroupSchedulingStrategy

# one process-wide cache per worker: compiled acting programs, the
# learner's collective group, and the last fetched param version
_PROC_CACHE: Dict[Any, Any] = {}


def _kv_key(namespace: str) -> bytes:
    return f"podracer/{namespace}/params".encode()


def _acting_programs(config):
    """Per-process jitted acting programs, keyed by what changes their
    XLA program (env, fragment length, net shape)."""
    import jax

    from .learner import make_acting_fns

    key = ("act", config.env, config.rollout_fragment_length,
           tuple(config.hiddens))
    progs = _PROC_CACHE.get(key)
    if progs is None:
        init_envs, act = make_acting_fns(
            config.env_cls, config.rollout_fragment_length
        )
        progs = (jax.jit(init_envs, static_argnums=1), jax.jit(act))
        _PROC_CACHE[key] = progs
    return progs


def _fetch_params(client, config):
    """Actor-side half of the version-tagged param channel: read the
    KV blob, decode only on version change."""
    key = ("params", config.namespace)
    blob = client.kv_get(_kv_key(config.namespace))
    if blob is None:
        raise RuntimeError(
            f"no published params on {_kv_key(config.namespace)!r} — "
            "SebulbaDriver publishes version 0 before the first round"
        )
    version, params = pickle.loads(blob)
    cached = _PROC_CACHE.get(key)
    if cached is not None and cached[0] == version:
        return cached
    _PROC_CACHE[key] = (version, params)
    return version, params


@ray_tpu.remote
def sample_fragment(cfg_blob: bytes, actor_idx: int, round_idx: int, carry):
    """One actor's rollout fragment. Pure function of (config, carry,
    published params): chaos-killed instances replay losslessly via
    lineage. Returns ``(traj, carry')`` via num_returns=2 — ``traj``
    is the big time-major batch (rides the object plane), ``carry'``
    the small env-state continuation the driver threads forward."""
    import jax

    from ray_tpu._private import worker

    config = pickle.loads(cfg_blob)
    client = worker.get_client()

    with tracing.span(
        "podracer.param_sync", stage="podracer.param_sync",
        role="actor", actor=actor_idx,
    ):
        version, params = _fetch_params(client, config)

    init_envs, act = _acting_programs(config)
    with tracing.span(
        "podracer.env_step", stage="podracer.env_step",
        role="actor", actor=actor_idx, round=round_idx,
    ):
        if carry is None:
            key = jax.random.fold_in(
                jax.random.PRNGKey(config.seed), 7919 + actor_idx
            )
            env_state, obs, ep_ret = init_envs(key, config.envs_per_actor)
        else:
            env_state = carry["env_state"]
            obs = carry["obs"]
            ep_ret = carry["ep_ret"]
        frag_key = jax.random.fold_in(
            jax.random.fold_in(
                jax.random.PRNGKey(config.seed), 104729 + actor_idx
            ),
            round_idx,
        )
        env_state, obs, ep_ret, batch, ep_sum, ep_n = act(
            params, env_state, obs, ep_ret, frag_key
        )
        jax.block_until_ready(batch)

    traj = {k: np.asarray(v) for k, v in batch.items()}
    traj["behavior_version"] = version
    new_carry = {
        "env_state": jax.tree_util.tree_map(np.asarray, env_state),
        "obs": np.asarray(obs),
        "ep_ret": np.asarray(ep_ret),
        "ep_sum": float(ep_sum),
        "ep_n": float(ep_n),
        "behavior_version": version,
    }
    return traj, new_carry


def _learner_group(config):
    key = ("group", config.namespace, config.learner_shards)
    group = _PROC_CACHE.get(key)
    if group is None:
        from ...util.collective.collective_group.xla_group import XlaGroup

        group = XlaGroup(
            config.learner_shards, 0, f"podracer-{config.namespace}"
        )
        _PROC_CACHE[key] = group
    return group


@ray_tpu.remote
def learner_update(cfg_blob: bytes, state, *trajs):
    """One learner round: ingest the handed-off fragments, run
    ``num_sgd_steps`` sharded updates (grad all-reduce over the
    collective group), publish params on the KV channel every
    ``param_sync_interval`` steps. Pure function of (state, trajs) —
    the KV publish is idempotent per version, so lineage retry after a
    worker_kill republishes the same bytes and resumes the counter."""
    import jax

    from ray_tpu._private import worker

    from .learner import make_sharded_update

    config = pickle.loads(cfg_blob)
    client = worker.get_client()
    spec = config.spec

    with tracing.span(
        "podracer.traj_handoff", stage="podracer.traj_handoff",
        fragments=len(trajs),
        bytes=sum(sum(a.nbytes for a in t.values()
                      if isinstance(a, np.ndarray)) for t in trajs),
    ):
        batch = {
            k: np.concatenate(
                [t[k] for t in trajs], axis=0 if k == "final_obs" else 1
            )
            for k in ("obs", "actions", "rewards", "dones", "logp_mu",
                      "final_obs")
        }
        batch = {k: jax.device_put(v) for k, v in batch.items()}

    group = _learner_group(config)
    _, update = make_sharded_update(config, spec, group)
    params = state["params"]
    opt_state = state["opt_state"]
    with tracing.span(
        "podracer.learner_update", stage="podracer.learner_update",
        step=state["step"] + 1, shards=group.world_size,
    ):
        for _ in range(config.num_sgd_steps):
            params, opt_state, metrics = update(params, opt_state, batch)
        jax.block_until_ready(params)

    step = state["step"] + 1
    version = state["version"]
    host_params = jax.tree_util.tree_map(np.asarray, params)
    new_state = {
        "params": host_params,
        "opt_state": jax.tree_util.tree_map(np.asarray, opt_state),
        "step": step,
        "version": version,
    }
    if step % config.param_sync_interval == 0:
        version = step
        new_state["version"] = version
        with tracing.span(
            "podracer.param_sync", stage="podracer.param_sync",
            role="learner", version=version,
        ):
            client.kv_put(
                _kv_key(config.namespace),
                pickle.dumps((version, host_params)),
            )
    out_metrics = {
        "step": step,
        "version": version,
        "behavior_versions": sorted(
            {int(t.get("behavior_version", -1)) for t in trajs}
        ),
        **{k: float(v) for k, v in metrics.items()},
    }
    return new_state, out_metrics


class SebulbaDriver:
    """Round-based driver: each round is one bulk-submitted actor
    fan-out plus one learner task chained on the state ref. Up to
    ``max_inflight_rounds`` learner rounds run behind the actors —
    the Sebulba decoupling: actors never block on the learner (params
    arrive via the KV channel), the driver never touches rollout
    bytes (they flow actor -> object plane -> learner by reference).
    """

    def __init__(self, config):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        config.validate()
        self.config = config
        self._cfg_blob = pickle.dumps(config)

        # gang placement: actors and learner on separate slices when
        # chips are present; on CPU hosts both degrade to PACK over
        # CPU bundles (resource reservation on a single host).
        strategy = config.placement_strategy
        if strategy is None:
            cluster = ray_tpu.cluster_resources()
            strategy = "SLICE" if cluster.get("TPU", 0) >= 1 else "PACK"
        bundle = {"TPU": 1} if strategy == "SLICE" else {"CPU": 1}
        from ...util.placement_group import placement_group

        self._pg_actors = placement_group(
            [dict(bundle) for _ in range(config.num_actors)],
            strategy=strategy, name="podracer-actors",
        )
        self._pg_learner = placement_group(
            [dict(bundle)], strategy=strategy, name="podracer-learner",
        )
        if not (self._pg_actors.wait(60) and self._pg_learner.wait(60)):
            raise RuntimeError("Podracer placement groups failed to place")

        self._sample = sample_fragment.options(
            num_returns=2,
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                self._pg_actors, -1
            ),
        )
        self._learn = learner_update.options(
            num_returns=2,
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                self._pg_learner, 0
            ),
        )

        # initial state: version 0 published before the first round so
        # the actor loop can always make progress
        import jax

        from ..core import init_mlp_module
        from .learner import make_optimizer

        params = init_mlp_module(
            jax.random.PRNGKey(config.seed), config.spec
        )
        opt_state = make_optimizer(config).init(params)
        host_params = jax.tree_util.tree_map(np.asarray, params)
        state = {
            "params": host_params,
            "opt_state": jax.tree_util.tree_map(np.asarray, opt_state),
            "step": 0,
            "version": 0,
        }
        from ray_tpu._private import worker as _worker

        _worker.get_client().kv_put(
            _kv_key(config.namespace),
            pickle.dumps((0, host_params)),
        )
        self._state_ref = ray_tpu.put(state)
        self._carries: List[Optional[dict]] = [None] * config.num_actors
        self._round = 0
        self._ep_sum = 0.0
        self._ep_n = 0.0
        self._last_metrics: Dict[str, Any] = {"step": 0, "version": 0}

    # -- round machinery ----------------------------------------------
    def _submit_round(self, inflight: deque) -> None:
        items = [
            (self._cfg_blob, i, self._round, self._carries[i])
            for i in range(self.config.num_actors)
        ]
        rows = self._sample.map(items)
        traj_refs = [row[0] for row in rows]
        carry_refs = [row[1] for row in rows]
        self._state_ref, metrics_ref = self._learn.remote(
            self._cfg_blob, self._state_ref, *traj_refs
        )
        # hold the traj refs until the learner round is harvested so
        # the segments can't be freed under an in-flight (or chaos-
        # retried) learner task
        inflight.append((metrics_ref, traj_refs))
        self._round += 1

        # the actors' small continuations: fetched eagerly (they gate
        # the next round anyway), harvested for episode stats
        carries = ray_tpu.get(carry_refs, timeout=300)
        self._carries = list(carries)
        for c in carries:
            self._ep_sum += c["ep_sum"]
            self._ep_n += c["ep_n"]

    def _harvest_one(self, inflight: deque) -> Dict[str, Any]:
        metrics_ref, _traj_refs = inflight.popleft()
        metrics = ray_tpu.get(metrics_ref, timeout=300)
        self._last_metrics = metrics
        return metrics

    def train(self, num_rounds: int) -> Dict[str, Any]:
        """Run ``num_rounds`` actor->learner rounds; returns throughput
        and learning stats. Actor rounds pipeline up to
        ``max_inflight_rounds`` ahead of the learner chain."""
        cfg = self.config
        inflight: deque = deque()
        learner_steps: List[int] = []
        round_returns: List[float] = []
        t0 = time.perf_counter()
        for _ in range(num_rounds):
            before_n, before_sum = self._ep_n, self._ep_sum
            self._submit_round(inflight)
            dn = self._ep_n - before_n
            round_returns.append(
                (self._ep_sum - before_sum) / dn if dn > 0 else float("nan")
            )
            while len(inflight) > cfg.max_inflight_rounds:
                learner_steps.append(self._harvest_one(inflight)["step"])
        while inflight:
            learner_steps.append(self._harvest_one(inflight)["step"])
        elapsed = time.perf_counter() - t0
        env_steps = (
            num_rounds * cfg.num_actors * cfg.envs_per_actor
            * cfg.rollout_fragment_length
        )
        return {
            "mode": "sebulba",
            "rounds": num_rounds,
            "env_steps": env_steps,
            "time_s": elapsed,
            "steps_per_sec": env_steps / elapsed if elapsed > 0 else 0.0,
            "learner_steps": learner_steps,
            "learner_step": self._last_metrics.get("step", 0),
            "param_version": self._last_metrics.get("version", 0),
            "episode_return_mean": (
                self._ep_sum / self._ep_n if self._ep_n > 0 else float("nan")
            ),
            "num_episodes": int(self._ep_n),
            "reward_trajectory": round_returns,
            "learner_metrics": dict(self._last_metrics),
        }

    def stop(self) -> None:
        from ...util.placement_group import remove_placement_group

        for pg in (self._pg_actors, self._pg_learner):
            try:
                remove_placement_group(pg)
            except Exception:
                pass
