"""SAC: off-policy maximum-entropy actor-critic for continuous control.

Parity: python/ray/rllib/algorithms/sac/ (twin critics, tanh-squashed
Gaussian policy, automatic entropy-coefficient tuning against
target_entropy=-|A|). TPU-native: the entire update — twin-critic
Bellman step, reparameterized actor step, alpha step, and the polyak
target sync — is ONE jitted program; rollout actors sample with a
jitted policy forward and ship flat numpy transitions.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .replay_buffers import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


@dataclass
class SACConfig:
    env: Optional[Union[str, Callable]] = None
    num_env_runners: int = 1
    num_envs_per_env_runner: int = 2
    rollout_fragment_length: int = 32
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005  # polyak target rate
    initial_alpha: float = 1.0
    target_entropy: Optional[float] = None  # default -action_dim
    buffer_capacity: int = 100_000
    train_batch_size: int = 256
    num_steps_sampled_before_learning_starts: int = 1000
    updates_per_iteration: int = 16  # sample rounds per train()
    train_intensity: int = 8  # gradient updates per sample round
    hiddens: Tuple[int, ...] = (256, 256)
    seed: int = 0

    def environment(self, env) -> "SACConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None) -> "SACConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "SACConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown SAC training param {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed=None) -> "SACConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build_algo(self) -> "SAC":
        return SAC(self)

    build = build_algo


# ------------------------------------------------------------------ nets
def _dense(key, fan_in, fan_out, gain=1.0):
    w = jax.nn.initializers.orthogonal(gain)(key, (fan_in, fan_out))
    return {"w": w, "b": jnp.zeros((fan_out,))}


def _mlp_init(key, sizes, out_dim, out_gain):
    keys = jax.random.split(key, len(sizes) + 1)
    layers = []
    fan_in = sizes[0]
    for i, h in enumerate(sizes[1:]):
        layers.append(_dense(keys[i], fan_in, h, np.sqrt(2.0)))
        fan_in = h
    return {"torso": layers, "head": _dense(keys[-1], fan_in, out_dim, out_gain)}


def _mlp_apply(params, x):
    for layer in params["torso"]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    return x @ params["head"]["w"] + params["head"]["b"]


def init_sac_params(rng, obs_dim: int, act_dim: int, hiddens) -> Dict[str, Any]:
    k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
    sizes = (obs_dim, *hiddens)
    q_sizes = (obs_dim + act_dim, *hiddens)
    return {
        "pi": _mlp_init(k_pi, sizes, 2 * act_dim, 0.01),  # mean ++ log_std
        "q1": _mlp_init(k_q1, q_sizes, 1, 1.0),
        "q2": _mlp_init(k_q2, q_sizes, 1, 1.0),
    }


def _policy_dist(pi_params, obs):
    out = _mlp_apply(pi_params, obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)


def _sample_squashed(pi_params, obs, rng):
    """Reparameterized tanh-Gaussian sample -> (action in [-1,1], logp)."""
    mean, log_std = _policy_dist(pi_params, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mean.shape)
    pre = mean + std * eps
    act = jnp.tanh(pre)
    # log prob with tanh change-of-variables (stable form)
    logp = (
        -0.5 * (eps**2 + 2 * log_std + jnp.log(2 * jnp.pi))
        - 2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre))
    ).sum(-1)
    return act, logp


def _q(params, obs, act):
    x = jnp.concatenate([obs, act], axis=-1)
    return _mlp_apply(params, x)[..., 0]


@jax.jit
def sample_actions(pi_params, obs, rng):
    act, _ = _sample_squashed(pi_params, obs, rng)
    return act


@jax.jit
def deterministic_actions(pi_params, obs):
    mean, _ = _policy_dist(pi_params, obs)
    return jnp.tanh(mean)


_UPDATE_CACHE: dict = {}


def make_sac_update(config: SACConfig, act_dim: int):
    import optax

    key = (config.actor_lr, config.critic_lr, config.alpha_lr, config.gamma,
           config.tau, config.target_entropy, act_dim, tuple(config.hiddens))
    cached = _UPDATE_CACHE.get(key)
    if cached is not None:
        return cached
    target_entropy = (
        config.target_entropy
        if config.target_entropy is not None
        else -float(act_dim)
    )
    actor_opt = optax.adam(config.actor_lr)
    critic_opt = optax.adam(config.critic_lr)
    alpha_opt = optax.adam(config.alpha_lr)

    def critic_loss_fn(q_params, pi_params, target_q, log_alpha, batch, rng):
        next_act, next_logp = _sample_squashed(pi_params, batch["next_obs"], rng)
        q_next = jnp.minimum(
            _q(target_q["q1"], batch["next_obs"], next_act),
            _q(target_q["q2"], batch["next_obs"], next_act),
        )
        alpha = jnp.exp(log_alpha)
        target = batch["rewards"] + config.gamma * (1.0 - batch["dones"]) * (
            q_next - alpha * next_logp
        )
        target = jax.lax.stop_gradient(target)
        l1 = jnp.mean((_q(q_params["q1"], batch["obs"], batch["actions"]) - target) ** 2)
        l2 = jnp.mean((_q(q_params["q2"], batch["obs"], batch["actions"]) - target) ** 2)
        return l1 + l2

    def actor_loss_fn(pi_params, q_params, log_alpha, batch, rng):
        act, logp = _sample_squashed(pi_params, batch["obs"], rng)
        q = jnp.minimum(
            _q(q_params["q1"], batch["obs"], act),
            _q(q_params["q2"], batch["obs"], act),
        )
        alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
        return jnp.mean(alpha * logp - q), logp

    @jax.jit
    def update(state, batch, rng):
        (params, target_q, log_alpha, opt_states) = state
        k1, k2 = jax.random.split(rng)
        q_params = {"q1": params["q1"], "q2": params["q2"]}
        closs, q_grads = jax.value_and_grad(critic_loss_fn)(
            q_params, params["pi"], target_q, log_alpha, batch, k1
        )
        q_updates, critic_os = critic_opt.update(
            q_grads, opt_states["critic"], q_params
        )
        q_params = optax.apply_updates(q_params, q_updates)

        (aloss, logp), pi_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(params["pi"], q_params, log_alpha, batch, k2)
        pi_updates, actor_os = actor_opt.update(
            pi_grads, opt_states["actor"], params["pi"]
        )
        pi_params = optax.apply_updates(params["pi"], pi_updates)

        # alpha step: match policy entropy to the target. Loss is
        # -log_alpha * E[logp + H_target]; its grad wrt log_alpha is
        # -E[gap]: entropy below target (gap > 0) drives log_alpha UP.
        entropy_gap = jax.lax.stop_gradient(logp + target_entropy)
        alpha_grad = -jnp.mean(entropy_gap)
        alpha_updates, alpha_os = alpha_opt.update(
            alpha_grad, opt_states["alpha"], log_alpha
        )
        log_alpha = optax.apply_updates(log_alpha, alpha_updates)

        # polyak target sync inside the same compiled program
        target_q = jax.tree.map(
            lambda t, s: (1 - config.tau) * t + config.tau * s,
            target_q,
            q_params,
        )
        new_params = {"pi": pi_params, "q1": q_params["q1"], "q2": q_params["q2"]}
        new_os = {"critic": critic_os, "actor": actor_os, "alpha": alpha_os}
        return (new_params, target_q, log_alpha, new_os), closs, aloss

    cached = (actor_opt, critic_opt, alpha_opt, update)
    _UPDATE_CACHE[key] = cached
    return cached


# ------------------------------------------------------------------ runner
class _GaussianRunner:
    """Rollout actor for continuous spaces: tanh-Gaussian exploration,
    actions stored normalized to [-1,1] (env sees the rescaled value)."""

    def __init__(self, env_creator, num_envs, seed, fragment):
        import gymnasium as gym

        if isinstance(env_creator, str):
            env_id = env_creator
            fns = [lambda: gym.make(env_id) for _ in range(num_envs)]
        else:
            fns = [env_creator for _ in range(num_envs)]
        self.envs = gym.vector.SyncVectorEnv(
            fns, autoreset_mode=gym.vector.AutoresetMode.SAME_STEP
        )
        space = self.envs.single_action_space
        self.low = np.asarray(space.low, np.float32)
        self.high = np.asarray(space.high, np.float32)
        self.num_envs = num_envs
        self.fragment = fragment
        self.seed = seed
        self._step = 0
        self.obs, _ = self.envs.reset(seed=seed)
        self._ep_returns = np.zeros(num_envs)
        self.completed: deque = deque(maxlen=100)  # trailing window (GL005)

    def space_dims(self):
        return (
            int(np.prod(self.envs.single_observation_space.shape)),
            int(np.prod(self.envs.single_action_space.shape)),
        )

    def action_bounds(self):
        return self.low, self.high

    def _to_env(self, act_norm):
        return self.low + (act_norm + 1.0) * 0.5 * (self.high - self.low)

    def sample(self, pi_params, random_actions: bool = False):
        T, N = self.fragment, self.num_envs
        obs_dim, act_dim = self.space_dims()
        out = {
            "obs": np.zeros((T * N, obs_dim), np.float32),
            "actions": np.zeros((T * N, act_dim), np.float32),
            "rewards": np.zeros((T * N,), np.float32),
            "next_obs": np.zeros((T * N, obs_dim), np.float32),
            "dones": np.zeros((T * N,), np.float32),
        }
        rng = np.random.default_rng(self.seed + self._step)
        obs = self.obs
        for t in range(T):
            if random_actions:
                act = rng.uniform(-1.0, 1.0, size=(N, act_dim)).astype(np.float32)
            else:
                key = jax.random.PRNGKey(self.seed * 100003 + self._step)
                act = np.asarray(
                    sample_actions(pi_params, jnp.asarray(obs, jnp.float32), key)
                )
            self._step += 1
            next_obs, rewards, term, trunc, infos = self.envs.step(self._to_env(act))
            from .env_runner import substitute_final_obs

            next_store = substitute_final_obs(next_obs, term, trunc, infos)
            sl = slice(t * N, (t + 1) * N)
            out["obs"][sl] = obs.reshape(N, -1)
            out["actions"][sl] = act
            out["rewards"][sl] = rewards
            out["next_obs"][sl] = next_store.reshape(N, -1)
            out["dones"][sl] = np.asarray(term, np.float32)
            self._ep_returns += rewards
            for i in np.nonzero(np.logical_or(term, trunc))[0]:
                self.completed.append(float(self._ep_returns[i]))
                self._ep_returns[i] = 0.0
            obs = next_obs
        self.obs = obs
        out["episode_returns"] = np.asarray(list(self.completed), np.float32)
        return out


# ------------------------------------------------------------------ algo
class SAC:
    def __init__(self, config: SACConfig):
        import ray_tpu

        if config.env is None:
            raise ValueError("config.environment(env) is required")
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.config = config
        self._ray = ray_tpu
        runner_cls = ray_tpu.remote(_GaussianRunner)
        self.env_runners = [
            runner_cls.remote(
                config.env, config.num_envs_per_env_runner,
                config.seed + 1000 * i, config.rollout_fragment_length,
            )
            for i in range(config.num_env_runners)
        ]
        obs_dim, act_dim = ray_tpu.get(self.env_runners[0].space_dims.remote())
        self.act_dim = act_dim
        self.action_low, self.action_high = ray_tpu.get(
            self.env_runners[0].action_bounds.remote()
        )
        self.params = init_sac_params(
            jax.random.PRNGKey(config.seed), obs_dim, act_dim, config.hiddens
        )
        self.target_q = jax.tree.map(
            lambda x: x, {"q1": self.params["q1"], "q2": self.params["q2"]}
        )
        self.log_alpha = jnp.asarray(np.log(config.initial_alpha), jnp.float32)
        actor_opt, critic_opt, alpha_opt, self._update = make_sac_update(
            config, act_dim
        )
        self.opt_states = {
            "critic": critic_opt.init(
                {"q1": self.params["q1"], "q2": self.params["q2"]}
            ),
            "actor": actor_opt.init(self.params["pi"]),
            "alpha": alpha_opt.init(self.log_alpha),
        }
        self.buffer = ReplayBuffer(config.buffer_capacity, seed=config.seed)
        self.iteration = 0
        self._timesteps = 0
        self._rng = jax.random.PRNGKey(config.seed + 777)

    def train(self) -> Dict[str, Any]:
        ray = self._ray
        c = self.config
        host_pi = jax.tree.map(np.asarray, self.params["pi"])
        # per-runner latest last-100 window (windows are cumulative per
        # runner, so keep only the newest per runner and concat across
        # runners — extending every round would double-count episodes)
        latest_windows: Dict[int, list] = {}
        closs = aloss = float("nan")
        for _ in range(c.updates_per_iteration):
            warmup = self._timesteps < c.num_steps_sampled_before_learning_starts
            rollouts = ray.get([
                r.sample.remote(host_pi, warmup) for r in self.env_runners
            ])
            for idx, ro in enumerate(rollouts):
                latest_windows[idx] = ro.pop("episode_returns").tolist()
                self.buffer.add(ro)
                self._timesteps += len(ro["actions"])
            if warmup or len(self.buffer) < c.train_batch_size:
                continue
            state = (self.params, self.target_q, self.log_alpha, self.opt_states)
            for _ in range(c.train_intensity):
                batch = self.buffer.sample(c.train_batch_size)
                self._rng, k = jax.random.split(self._rng)
                state, cl, al = self._update(state, batch, k)
                closs, aloss = float(cl), float(al)
            (self.params, self.target_q, self.log_alpha, self.opt_states) = state
            host_pi = jax.tree.map(np.asarray, self.params["pi"])
        from .env_runner import merge_return_windows

        episode_returns = merge_return_windows(latest_windows)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "episode_return_mean": (
                float(np.mean(episode_returns)) if episode_returns
                else float("nan")
            ),
            "num_episodes": len(episode_returns),
            "critic_loss": closs,
            "actor_loss": aloss,
            "alpha": float(jnp.exp(self.log_alpha)),
            "buffer_size": len(self.buffer),
        }

    def compute_single_action(self, obs) -> np.ndarray:
        """Deterministic ENV-SPACE action (the runner applies the same
        rescale before env.step; RLlib returns env-space actions too)."""
        act = np.asarray(
            deterministic_actions(
                self.params["pi"], jnp.asarray(obs, jnp.float32)[None]
            )[0]
        )
        return self.action_low + (act + 1.0) * 0.5 * (
            self.action_high - self.action_low
        )

    def stop(self) -> None:
        for r in self.env_runners:
            try:
                self._ray.kill(r)
            except Exception:
                pass
        self.env_runners = []
