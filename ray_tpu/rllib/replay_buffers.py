"""Replay buffers (reference: rllib/utils/replay_buffers/).

Ring-buffer storage in preallocated numpy arrays (O(1) add, vectorized
uniform sampling) — the TPU-friendly layout: sample() returns contiguous
arrays that device_put straight into the jitted learner.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform-sampling FIFO replay (reference: ReplayBuffer /
    EpisodeReplayBuffer storage semantics)."""

    def __init__(self, capacity: int, seed: Optional[int] = None):
        self.capacity = capacity
        self._arrays: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        """Add a batch of transitions {key: (N, ...)}."""
        n = len(next(iter(batch.values())))
        if self._arrays is None:
            self._arrays = {
                k: np.zeros((self.capacity, *np.asarray(v).shape[1:]),
                            np.asarray(v).dtype)
                for k, v in batch.items()
            }
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._next + np.arange(n)) % self.capacity
            self._arrays[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        if self._size == 0:
            raise ValueError("cannot sample from an empty buffer")
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: a[idx] for k, a in self._arrays.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (Schaul et al.; reference:
    rllib PrioritizedReplayBuffer). Priorities default to the max seen
    so new transitions are sampled at least once."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._prios = np.zeros(capacity, np.float64)
        self._max_prio = 1.0
        self._last_idx: Optional[np.ndarray] = None

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        idx = (self._next + np.arange(n)) % self.capacity
        super().add(batch)
        self._prios[idx] = self._max_prio

    def sample(self, batch_size: int, beta: float = 0.4) -> Dict[str, np.ndarray]:
        if self._size == 0:
            raise ValueError("cannot sample from an empty buffer")
        p = self._prios[: self._size] ** self.alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=p)
        self._last_idx = idx
        out = {k: a[idx] for k, a in self._arrays.items()}
        weights = (self._size * p[idx]) ** (-beta)
        out["weights"] = (weights / weights.max()).astype(np.float32)
        return out

    def update_priorities(self, td_errors: np.ndarray, eps: float = 1e-6) -> None:
        assert self._last_idx is not None, "sample() before update_priorities()"
        prios = np.abs(td_errors) + eps
        self._prios[self._last_idx] = prios
        self._max_prio = max(self._max_prio, float(prios.max()))
