"""BC: offline behavior cloning from a ray_tpu.data Dataset.

Parity: python/ray/rllib/algorithms/bc/ + the offline data path
(rllib/offline/ reading experiences through Ray Data). The dataset
provides "obs" and "actions" columns; training is plain supervised
cross-entropy on the policy head, batched through
``Dataset.iter_batches`` so the offline pipeline (reads, maps,
shuffles) is the same Data machinery online algorithms use for
everything else.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core import MLPSpec, forward, init_mlp_module


@dataclass
class BCConfig:
    lr: float = 1e-3
    train_batch_size: int = 256
    hiddens: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def training(self, **kwargs) -> "BCConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown BC training param {k!r}")
            setattr(self, k, v)
        return self

    def build_algo(self, obs_dim: int, num_actions: int) -> "BC":
        return BC(self, obs_dim, num_actions)


class BC:
    def __init__(self, config: BCConfig, obs_dim: int, num_actions: int):
        import optax

        self.config = config
        self.spec = MLPSpec(obs_dim, num_actions, tuple(config.hiddens))
        self.params = init_mlp_module(
            jax.random.PRNGKey(config.seed), self.spec
        )
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, obs, actions):
            logits, _ = forward(params, obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, actions[:, None], axis=1)[:, 0]
            return jnp.mean(nll)

        @jax.jit
        def update(params, opt_state, obs, actions):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs, actions)
            updates, opt_state = self.optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = update
        self.iteration = 0

    def train_on_dataset(self, dataset, *, epochs: int = 1) -> Dict[str, Any]:
        """Offline training pass(es) over a Dataset with "obs" and
        "actions" columns (the rllib/offline shape)."""
        losses = []
        n = 0
        for _ in range(epochs):
            for batch in dataset.iter_batches(
                batch_size=self.config.train_batch_size, batch_format="numpy"
            ):
                obs = np.asarray(batch["obs"], np.float32).reshape(
                    len(batch["actions"]), -1
                )
                actions = np.asarray(batch["actions"], np.int64)
                self.params, self.opt_state, loss = self._update(
                    self.params, self.opt_state, obs, actions
                )
                losses.append(float(loss))
                n += len(actions)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "num_samples_trained": n,
            "loss": float(np.mean(losses)) if losses else float("nan"),
        }

    def compute_single_action(self, obs) -> int:
        logits, _ = forward(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(logits[0]))
