"""APPO: asynchronous PPO — IMPALA's actor-learner with PPO's clipped
surrogate.

Parity: python/ray/rllib/algorithms/appo/ — same async sampling
architecture as IMPALA (stale behavior policies, V-trace correction)
but the policy loss is the PPO clipped surrogate over the V-trace
advantages, which tolerates more staleness than the plain V-trace
policy-gradient. Reuses IMPALA's runner fan-out and jit shape; only
the compiled loss differs.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .core import MLPSpec, forward
from .impala import IMPALA, IMPALAConfig, vtrace

_UPDATE_CACHE: dict = {}


@dataclass
class APPOConfig(IMPALAConfig):
    """Builder (reference: appo.py APPOConfig — clip_param on top of the
    IMPALA knobs)."""

    clip_param: float = 0.3

    def build_algo(self):
        return APPO(self)

    build = build_algo


def make_appo_loss(config, spec: MLPSpec):
    """APPO's clipped-surrogate-over-V-trace loss as a standalone
    ``loss_fn(params, batch) -> (total, metrics)``. ``config``
    duck-types APPOConfig (adds clip_param on top of the IMPALA
    hyperparams); reused by the Podracer learners the same way
    ``make_impala_loss`` is."""

    def loss_fn(params, batch):
        logits, values = forward(params, batch["obs"])  # (T, B, A), (T, B)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        bootstrap = forward(params, batch["final_obs"])[1]
        vs, pg_adv = vtrace(
            batch["logp_mu"], jax.lax.stop_gradient(logp),
            batch["rewards"], batch["dones"],
            jax.lax.stop_gradient(values), jax.lax.stop_gradient(bootstrap),
            gamma=config.gamma,
            clip_rho=config.vtrace_clip_rho,
            clip_c=config.vtrace_clip_c,
        )
        adv = jax.lax.stop_gradient(pg_adv)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        # PPO clipped surrogate against the BEHAVIOR policy (the APPO
        # twist: ratio is new-policy vs rollout-time policy)
        ratio = jnp.exp(logp - batch["logp_mu"])
        clipped = jnp.clip(ratio, 1 - config.clip_param, 1 + config.clip_param)
        pi_loss = -jnp.mean(jnp.minimum(ratio * adv, clipped * adv))
        vf_loss = jnp.mean((values - jax.lax.stop_gradient(vs)) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = (
            pi_loss
            + config.vf_loss_coeff * vf_loss
            - config.entropy_coeff * entropy
        )
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_ratio": jnp.mean(jax.lax.stop_gradient(ratio)),
        }

    return loss_fn


def make_appo_update(config: APPOConfig, spec: MLPSpec):
    import optax

    key = (
        config.lr, config.gamma, config.vtrace_clip_rho,
        config.vtrace_clip_c, config.vf_loss_coeff, config.entropy_coeff,
        config.grad_clip, config.clip_param, spec,
    )
    cached = _UPDATE_CACHE.get(key)
    if cached is not None:
        return cached

    optimizer = optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.adam(config.lr),
    )

    loss_fn = make_appo_loss(config, spec)

    @jax.jit
    def update(params, opt_state, batch):
        (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    _UPDATE_CACHE[key] = (optimizer, update)
    return optimizer, update


class APPO(IMPALA):
    _make_update = staticmethod(make_appo_update)
