"""ray_tpu.rllib — reinforcement learning.

Parity: python/ray/rllib/ core shape (AlgorithmConfig builder →
Algorithm.train(); EnvRunner actor fan-out; jitted Learner update).
PPO (sync batch) + IMPALA (async actor-learner with V-trace, §2.5) +
the Podracer layouts (Anakin/Sebulba, ``podracer/``).
"""

from .algorithm import Algorithm
from .appo import APPO, APPOConfig
from .bc import BC, BCConfig
from .connectors import (
    ConnectorPipelineV2,
    ConnectorV2,
    FlattenObservations,
    FrameStackObservations,
    NormalizeObservations,
)
from .core import MLPSpec, forward, init_mlp_module, sample_actions
from .cql import CQL, CQLConfig
from .env_runner import SingleAgentEnvRunner
from .dqn import DQN, DQNConfig
from .impala import IMPALA, IMPALAConfig, vtrace
from .marwil import MARWIL, MARWILConfig
from .multi_agent import (
    MultiAgentAlgorithm,
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentEpisode,
    make_multi_agent,
)
from .replay_buffers import PrioritizedReplayBuffer, ReplayBuffer
from .podracer import PodracerConfig
from .ppo import PPOConfig
from .sac import SAC, SACConfig

__all__ = [
    "Algorithm",
    "ConnectorPipelineV2",
    "ConnectorV2",
    "FlattenObservations",
    "FrameStackObservations",
    "NormalizeObservations",
    "APPO",
    "APPOConfig",
    "BC",
    "BCConfig",
    "CQL",
    "CQLConfig",
    "DQN",
    "DQNConfig",
    "IMPALA",
    "IMPALAConfig",
    "MARWIL",
    "MARWILConfig",
    "MLPSpec",
    "MultiAgentAlgorithm",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "MultiAgentEpisode",
    "make_multi_agent",
    "PodracerConfig",
    "PPOConfig",
    "SAC",
    "SACConfig",
    "SingleAgentEnvRunner",
    "forward",
    "init_mlp_module",
    "sample_actions",
    "vtrace",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("rllib")
