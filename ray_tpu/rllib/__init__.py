"""ray_tpu.rllib — reinforcement learning.

Parity: python/ray/rllib/ core shape (AlgorithmConfig builder →
Algorithm.train(); EnvRunner actor fan-out; jitted Learner update).
PPO first; the actor/learner pattern generalizes (§2.5).
"""

from .algorithm import Algorithm
from .core import MLPSpec, forward, init_mlp_module, sample_actions
from .env_runner import SingleAgentEnvRunner
from .ppo import PPOConfig

__all__ = [
    "Algorithm",
    "MLPSpec",
    "PPOConfig",
    "SingleAgentEnvRunner",
    "forward",
    "init_mlp_module",
    "sample_actions",
]
