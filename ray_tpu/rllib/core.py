"""RLModule: the neural-net abstraction (jax-native).

Parity: python/ray/rllib/core/rl_module/ — a module owns inference /
exploration / train forwards. Here a module is a pure-function pair
(init, apply) over a params pytree: jit/pjit-ready, no framework
objects crossing process boundaries (EnvRunner actors receive plain
arrays).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class MLPSpec:
    """Policy+value network spec (reference analogue: RLModule catalog
    defaults — fcnet_hiddens)."""

    obs_dim: int
    num_actions: int
    hiddens: Tuple[int, ...] = (64, 64)
    dtype: Any = jnp.float32


def init_mlp_module(rng: jax.Array, spec: MLPSpec) -> Dict[str, Any]:
    """Shared torso + policy and value heads."""

    def dense(key, fan_in, fan_out):
        scale = 1.0 / math.sqrt(fan_in)
        return {
            "w": (jax.random.normal(key, (fan_in, fan_out)) * scale).astype(spec.dtype),
            "b": jnp.zeros((fan_out,), spec.dtype),
        }

    keys = jax.random.split(rng, len(spec.hiddens) + 2)
    layers = []
    fan_in = spec.obs_dim
    for i, h in enumerate(spec.hiddens):
        layers.append(dense(keys[i], fan_in, h))
        fan_in = h
    return {
        "torso": layers,
        "pi": dense(keys[-2], fan_in, spec.num_actions),
        "vf": dense(keys[-1], fan_in, 1),
    }


def forward(params: Dict[str, Any], obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs (B, obs_dim) -> (logits (B, A), value (B,))."""
    x = obs
    for layer in params["torso"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def sample_actions(
    params: Dict[str, Any], obs: jax.Array, rng: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """-> (actions, logp, value) for exploration rollouts."""
    logits, value = forward(params, obs)
    actions = jax.random.categorical(rng, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(obs.shape[0]), actions]
    return actions, logp, value
