"""RLModule: the neural-net abstraction (jax-native).

Parity: python/ray/rllib/core/rl_module/ — a module owns inference /
exploration / train forwards. Here a module is a pure-function pair
(init, apply) over a params pytree: jit/pjit-ready, no framework
objects crossing process boundaries (EnvRunner actors receive plain
arrays).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class MLPSpec:
    """Policy+value network spec (reference analogue: RLModule catalog
    defaults — fcnet_hiddens)."""

    obs_dim: int
    num_actions: int
    hiddens: Tuple[int, ...] = (64, 64)
    dtype: Any = jnp.float32


def init_mlp_module(rng: jax.Array, spec: MLPSpec) -> Dict[str, Any]:
    """Separate policy and value torsos with orthogonal init.

    Mirrors the reference's RLlib catalog defaults (vf_share_layers=False)
    and the standard PPO init recipe: orthogonal(sqrt(2)) hidden layers,
    orthogonal(0.01) policy head, orthogonal(1.0) value head — the small
    policy-head gain keeps the initial policy near-uniform so early value
    errors can't collapse exploration.
    """

    def dense(key, fan_in, fan_out, gain):
        w = jax.nn.initializers.orthogonal(gain)(key, (fan_in, fan_out))
        return {
            "w": w.astype(spec.dtype),
            "b": jnp.zeros((fan_out,), spec.dtype),
        }

    def mlp(key, head_out, head_gain):
        keys = jax.random.split(key, len(spec.hiddens) + 1)
        layers = []
        fan_in = spec.obs_dim
        for i, h in enumerate(spec.hiddens):
            layers.append(dense(keys[i], fan_in, h, math.sqrt(2.0)))
            fan_in = h
        head = dense(keys[-1], fan_in, head_out, head_gain)
        return layers, head

    k_pi, k_vf = jax.random.split(rng)
    pi_torso, pi_head = mlp(k_pi, spec.num_actions, 0.01)
    vf_torso, vf_head = mlp(k_vf, 1, 1.0)
    return {
        "pi_torso": pi_torso,
        "pi": pi_head,
        "vf_torso": vf_torso,
        "vf": vf_head,
    }


def _mlp_forward(layers, head, x):
    for layer in layers:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    return x @ head["w"] + head["b"]


def forward(params: Dict[str, Any], obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs (B, obs_dim) -> (logits (B, A), value (B,))."""
    logits = _mlp_forward(params["pi_torso"], params["pi"], obs)
    value = _mlp_forward(params["vf_torso"], params["vf"], obs)[..., 0]
    return logits, value


@jax.jit
def sample_actions(
    params: Dict[str, Any], obs: jax.Array, rng: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """-> (actions, logp, value) for exploration rollouts. Jitted: the
    rollout hot loop calls this once per vector-env step."""
    logits, value = forward(params, obs)
    actions = jax.random.categorical(rng, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(obs.shape[0]), actions]
    return actions, logp, value


@jax.jit
def values_only(params: Dict[str, Any], obs: jax.Array) -> jax.Array:
    """Batched V(s) for truncation bootstraps (jitted)."""
    return forward(params, obs)[1]
