from ray_tpu.scripts import main

main()
