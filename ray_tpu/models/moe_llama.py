"""Mixtral-style MoE Llama: the flagship architecture with every dense
FFN replaced by a top-k routed expert FFN.

Second first-class model family (the reference ships none in-tree —
it serves models through vLLM; here models are in-tree and mesh-aware).
Reuses the Llama attention stack (GQA/RoPE/RMSNorm, stacked-layer scan,
flash/ring attention impls) from models/llama.py and the capacity-
bounded expert dispatch from ops/moe.py; experts shard over the
`expert` mesh axis (param_specs), tokens reach them via GSPMD
all-to-all — the §2.5 EP strategy as a real model.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.ops import MoEConfig, moe_ffn

from .llama import (
    LlamaConfig,
    attention_sublayer,
    attn_param_count,
    init_attn_params,
    make_dense_init,
    masked_ce,
    rms_norm,
    rope_table,
    unpack_batch,
)


@dataclasses.dataclass(frozen=True)
class MoELlamaConfig(LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_coeff: float = 0.01

    @property
    def moe(self) -> MoEConfig:
        return MoEConfig(
            d_model=self.dim,
            d_ff=self.ffn_dim,
            n_experts=self.n_experts,
            k=self.experts_per_token,
            capacity_factor=self.capacity_factor,
        )


# Stock shapes (public Mixtral architecture table) + test-size config.
MIXTRAL_8X7B = MoELlamaConfig(
    vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    ffn_dim=14336, max_seq_len=32768, rope_theta=1e6,
    n_experts=8, experts_per_token=2,
)
MOE_TINY = MoELlamaConfig(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=128, max_seq_len=128, rope_theta=10000.0, remat=False,
    n_experts=4, experts_per_token=2,
)


def param_specs(config: MoELlamaConfig) -> Dict[str, Any]:
    """Llama attention shardings + experts on the `expert` axis.

    Expert matrices are (L, E, D, F): E shards over `expert` (EP), and
    the per-expert matrices additionally shard fsdp/model exactly like
    the dense FFN — EP composes with TP/FSDP."""
    return {
        "embed": P("model", "fsdp"),
        "blocks": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "model", None),
            "wk": P(None, "fsdp", "model", None),
            "wv": P(None, "fsdp", "model", None),
            "wo": P(None, "model", None, "fsdp"),
            "mlp_norm": P(None, None),
            "router": P(None, "fsdp", None),            # (L, D, E)
            "w_gate": P(None, "expert", "fsdp", "model"),  # (L, E, D, F)
            "w_up": P(None, "expert", "fsdp", "model"),
            "w_down": P(None, "expert", "model", "fsdp"),  # (L, E, F, D)
        },
        "final_norm": P(None),
        "lm_head": P("fsdp", "model"),
    }


def init_params(rng: jax.Array, config: MoELlamaConfig) -> Dict[str, Any]:
    c = config
    keys = jax.random.split(rng, 10)
    (k_embed, k_q, k_k, k_v, k_o, k_r, k_g, k_u, k_d, k_lm) = keys
    dense = make_dense_init(c)
    L, E = c.n_layers, c.n_experts
    return {
        "embed": dense(k_embed, (c.vocab_size, c.dim), c.dim),
        "blocks": {
            **init_attn_params(c, (k_q, k_k, k_v, k_o), dense),
            # router stays float32: tiny, and routing is precision-
            # sensitive (standard MoE practice)
            "router": (
                jax.random.normal(k_r, (L, c.dim, E)) / math.sqrt(c.dim)
            ).astype(jnp.float32),
            "w_gate": dense(k_g, (L, E, c.dim, c.ffn_dim), c.dim),
            "w_up": dense(k_u, (L, E, c.dim, c.ffn_dim), c.dim),
            "w_down": dense(k_d, (L, E, c.ffn_dim, c.dim), c.ffn_dim),
        },
        "final_norm": jnp.ones((c.dim,), c.param_dtype),
        "lm_head": dense(k_lm, (c.dim, c.vocab_size), c.dim),
    }


def param_count(config: MoELlamaConfig) -> int:
    c = config
    moe = c.dim * c.n_experts + 3 * c.n_experts * c.dim * c.ffn_dim
    return (
        c.vocab_size * c.dim * 2
        + c.n_layers * (attn_param_count(c) + moe)
        + c.dim
    )


def active_param_count(config: MoELlamaConfig) -> int:
    """Params touched per token (k of E experts) — the FLOPs-relevant
    count for MFU math on MoE models."""
    c = config
    moe = c.dim * c.n_experts + 3 * c.experts_per_token * c.dim * c.ffn_dim
    return (
        c.vocab_size * c.dim * 2
        + c.n_layers * (attn_param_count(c) + moe)
        + c.dim
    )


def block_fn(config: MoELlamaConfig, x: jax.Array, layer: Dict[str, jax.Array],
             cos: jax.Array, sin: jax.Array, mask=None):
    """One MoE transformer block. Returns (x, aux_loss)."""
    c = config
    x = attention_sublayer(c, x, layer, cos, sin)

    h = rms_norm(x, layer["mlp_norm"], c.norm_eps)
    moe_params = {
        # router stays fp32 (precision-sensitive); expert matmuls — the
        # bulk of the FLOPs — run in config.dtype like the dense FFN
        "router": layer["router"],
        "w_gate": layer["w_gate"].astype(c.dtype),
        "w_up": layer["w_up"].astype(c.dtype),
        "w_down": layer["w_down"].astype(c.dtype),
    }
    out, aux = moe_ffn(moe_params, h.astype(c.dtype), c.moe, mask=mask)
    return x + out.astype(x.dtype), aux


def forward(params: Dict[str, Any], tokens: jax.Array,
            config: MoELlamaConfig, mask=None):
    """tokens (B, S) int32 -> (logits (B, S, V) float32, aux_loss).

    Same stacked-layer lax.scan shape as the dense model; the router
    aux losses accumulate through the scan carry. ``mask`` (B, S)
    excludes padding tokens from expert capacity and balance stats."""
    c = config
    B, S = tokens.shape
    x = params["embed"].astype(c.dtype)[tokens]
    cos, sin = rope_table(c, S)

    blk = partial(block_fn, c)
    if c.remat:
        blk = jax.checkpoint(
            blk, policy=jax.checkpoint_policies.nothing_saveable
        )

    def scan_body(carry, layer):
        x, aux_sum = carry
        x, aux = blk(x, layer, cos, sin, mask)
        return (x, aux_sum + aux), None

    (x, aux_sum), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["blocks"]
    )
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"].astype(c.dtype))
    logits = logits.astype(jnp.float32)
    if c.logit_softcap:
        logits = jnp.tanh(logits / c.logit_softcap) * c.logit_softcap
    return logits, aux_sum / c.n_layers


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            config: MoELlamaConfig) -> jax.Array:
    """Next-token cross entropy + router load-balancing aux loss.

    The LOSS mask ("mask") and the ROUTING mask are different things:
    an SFT loss mask zeroes prompt positions whose tokens are still
    real input the experts must process. Routing only excludes PADDING,
    supplied as batch["input_mask"] aligned with the model inputs; when
    absent, every input position routes."""
    inputs, targets, mask = unpack_batch(batch)
    input_mask = batch.get("input_mask")
    if input_mask is not None and "tokens" in batch:
        input_mask = input_mask[:, :-1]  # align with inputs = tokens[:, :-1]
    logits, aux = forward(params, inputs, config, mask=input_mask)
    return masked_ce(logits, targets, mask) + config.router_aux_coeff * aux
