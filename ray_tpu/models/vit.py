"""ViT: vision transformer model family.

Third first-class model family (dense Llama, MoE Llama, ViT) — the
vision counterpart: patchify -> transformer encoder (pre-norm, GELU
MLP, learned position embeddings, CLS token) -> classification head.
TPU-first shape: patchify is one einsum-friendly reshape + projection
(no conv kernels needed), the encoder runs as a stacked-layer
lax.scan exactly like the Llama families, and param_specs shard
attention heads / MLP over the `model` axis with `fsdp` on the
embedding dims — the same mesh contract every trainer in this repo
speaks.

Reference parity: the reference ships no in-tree models (vision flows
through torch downstream); in-tree families are what give Train/Serve/
Data first-class workloads here.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    channels: int = 3
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels


VIT_B_16 = ViTConfig()
VIT_L_16 = ViTConfig(dim=1024, n_layers=24, n_heads=16, mlp_dim=4096)
VIT_TINY = ViTConfig(
    image_size=32, patch_size=8, num_classes=10, dim=64, n_layers=2,
    n_heads=4, mlp_dim=128, remat=False, dtype=jnp.float32,
)


def param_specs(config: ViTConfig) -> Dict[str, Any]:
    """Mesh contract shared with the Llama families: heads/MLP on
    `model`, embedding-like dims on `fsdp`."""
    return {
        "patch_proj": P(None, "fsdp"),            # (patch_dim, D)
        "patch_bias": P(None),
        "cls": P(None),                            # (D,)
        "pos": P(None, "fsdp"),                    # (1+N, D)
        "blocks": {
            "ln1_scale": P(None, None),
            "ln1_bias": P(None, None),
            "wq": P(None, "fsdp", "model", None),  # (L, D, H, hd)
            "wk": P(None, "fsdp", "model", None),
            "wv": P(None, "fsdp", "model", None),
            "wo": P(None, "model", None, "fsdp"),
            "ln2_scale": P(None, None),
            "ln2_bias": P(None, None),
            "w1": P(None, "fsdp", "model"),        # (L, D, M)
            "b1": P(None, "model"),
            "w2": P(None, "model", "fsdp"),        # (L, M, D)
            "b2": P(None, None),
        },
        "head_norm_scale": P(None),
        "head_norm_bias": P(None),
        "head": P("fsdp", None),                   # (D, classes)
        "head_bias": P(None),
    }


def init_params(rng: jax.Array, config: ViTConfig) -> Dict[str, Any]:
    c = config
    hd = c.head_dim
    L = c.n_layers
    keys = jax.random.split(rng, 9)
    (k_patch, k_cls, k_pos, k_q, k_k, k_v, k_o, k_mlp, k_head) = keys

    def dense(key, shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(
            c.param_dtype
        )

    k1, k2 = jax.random.split(k_mlp)
    return {
        "patch_proj": dense(k_patch, (c.patch_dim, c.dim), c.patch_dim),
        "patch_bias": jnp.zeros((c.dim,), c.param_dtype),
        "cls": (jax.random.normal(k_cls, (c.dim,)) * 0.02).astype(c.param_dtype),
        "pos": (
            jax.random.normal(k_pos, (1 + c.n_patches, c.dim)) * 0.02
        ).astype(c.param_dtype),
        "blocks": {
            "ln1_scale": jnp.ones((L, c.dim), c.param_dtype),
            "ln1_bias": jnp.zeros((L, c.dim), c.param_dtype),
            "wq": dense(k_q, (L, c.dim, c.n_heads, hd), c.dim),
            "wk": dense(k_k, (L, c.dim, c.n_heads, hd), c.dim),
            "wv": dense(k_v, (L, c.dim, c.n_heads, hd), c.dim),
            "wo": dense(k_o, (L, c.n_heads, hd, c.dim), c.dim),
            "ln2_scale": jnp.ones((L, c.dim), c.param_dtype),
            "ln2_bias": jnp.zeros((L, c.dim), c.param_dtype),
            "w1": dense(k1, (L, c.dim, c.mlp_dim), c.dim),
            "b1": jnp.zeros((L, c.mlp_dim), c.param_dtype),
            "w2": dense(k2, (L, c.mlp_dim, c.dim), c.mlp_dim),
            "b2": jnp.zeros((L, c.dim), c.param_dtype),
        },
        "head_norm_scale": jnp.ones((c.dim,), c.param_dtype),
        "head_norm_bias": jnp.zeros((c.dim,), c.param_dtype),
        "head": dense(k_head, (c.dim, c.num_classes), c.dim),
        "head_bias": jnp.zeros((c.num_classes,), c.param_dtype),
    }


def param_count(config: ViTConfig) -> int:
    params = init_params(jax.random.PRNGKey(0), config)
    import numpy as np

    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))


def _layer_norm(x, scale, bias, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    return out * scale.astype(x.dtype) + bias.astype(x.dtype)


def patchify(images: jax.Array, config: ViTConfig) -> jax.Array:
    """(B, H, W, C) -> (B, N, patch_dim) without convolutions: a
    reshape/transpose XLA fuses into the projection matmul."""
    c = config
    B, H, W, C = images.shape
    p = c.patch_size
    x = images.reshape(B, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, h, w, p, p, C)
    return x.reshape(B, c.n_patches, c.patch_dim)


def block_fn(config: ViTConfig, x: jax.Array, layer: Dict[str, jax.Array]):
    c = config
    h = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"], c.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(c.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(c.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(c.dtype))
    logits = jnp.einsum("bqhk,bthk->bhqt", q, k) / math.sqrt(c.head_dim)
    attn = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(c.dtype)
    o = jnp.einsum("bhqt,bthk->bqhk", attn, v)
    x = x + jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(c.dtype))

    h = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"], c.norm_eps)
    h = jax.nn.gelu(
        jnp.einsum("bsd,dm->bsm", h, layer["w1"].astype(c.dtype))
        + layer["b1"].astype(c.dtype)
    )
    x = x + (
        jnp.einsum("bsm,md->bsd", h, layer["w2"].astype(c.dtype))
        + layer["b2"].astype(c.dtype)
    )
    return x


def forward(params: Dict[str, Any], images: jax.Array,
            config: ViTConfig) -> jax.Array:
    """images (B, H, W, C) float -> class logits (B, num_classes) f32."""
    c = config
    B = images.shape[0]
    patches = patchify(images.astype(c.dtype), c)
    x = (
        jnp.einsum("bnp,pd->bnd", patches, params["patch_proj"].astype(c.dtype))
        + params["patch_bias"].astype(c.dtype)
    )
    cls = jnp.broadcast_to(params["cls"].astype(c.dtype), (B, 1, c.dim))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"].astype(c.dtype)

    blk = partial(block_fn, c)
    if c.remat:
        blk = jax.checkpoint(blk, policy=jax.checkpoint_policies.nothing_saveable)

    def scan_body(carry, layer):
        return blk(carry, layer), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    x = _layer_norm(
        x[:, 0], params["head_norm_scale"], params["head_norm_bias"], c.norm_eps
    )
    logits = (
        jnp.einsum("bd,dk->bk", x, params["head"].astype(c.dtype))
        + params["head_bias"].astype(c.dtype)
    )
    return logits.astype(jnp.float32)


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            config: ViTConfig) -> jax.Array:
    """Softmax CE over classes; batch {"image": (B,H,W,C), "label": (B,)}."""
    logits = forward(params, batch["image"], config)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(
        jnp.take_along_axis(
            logp, batch["label"][:, None].astype(jnp.int32), axis=1
        )
    )
