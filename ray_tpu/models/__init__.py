"""Model zoo: TPU-first reference models used by Train/Serve/Data/RLlib.

The reference (Ray) delegates model code to torch/vLLM downstream; this
framework ships JAX-native models so its ML libraries have first-class
workloads (flagship: Llama — BASELINE.json north star).
"""

from . import llama, moe_llama, vit
from .llama import (
    LLAMA_2_7B,
    LLAMA_3_8B,
    LLAMA_3_70B,
    LLAMA_BENCH,
    LLAMA_TINY,
    LlamaConfig,
)
from .moe_llama import MIXTRAL_8X7B, MOE_TINY, MoELlamaConfig
from .vit import VIT_B_16, VIT_L_16, VIT_TINY, ViTConfig

__all__ = [
    "llama",
    "moe_llama",
    "vit",
    "ViTConfig",
    "VIT_B_16",
    "VIT_L_16",
    "VIT_TINY",
    "LlamaConfig",
    "LLAMA_2_7B",
    "LLAMA_3_8B",
    "LLAMA_3_70B",
    "LLAMA_BENCH",
    "LLAMA_TINY",
    "MoELlamaConfig",
    "MIXTRAL_8X7B",
    "MOE_TINY",
]
