"""Llama-family decoder-only transformer, TPU-first.

Flagship model for the framework (BASELINE.json north star: Llama-3-8B
on TPU pods). Design choices are deliberately XLA-shaped rather than a
torch translation:

- Parameters are a flat pytree of arrays with **stacked layers**
  (leading ``n_layers`` axis) consumed by ``lax.scan`` — one compiled
  block instead of n_layers unrolled copies, so compile time and HBM
  code size stay flat as depth grows.
- Attention/MLP matmuls are einsums in bfloat16 feeding the MXU; the
  attention inner can be swapped for the Pallas flash kernel
  (ray_tpu.ops.attention) via ``config.attention_impl``.
- Sharding is declared as PartitionSpecs per parameter (``param_specs``)
  against the canonical mesh axes (ray_tpu.parallel.mesh): fsdp shards
  the "long" dim of each matrix, model (tensor parallel) shards heads /
  ffn-hidden, Megatron-style, with XLA GSPMD inserting the collectives.
- GQA (n_kv_heads < n_heads), RoPE, RMSNorm, SwiGLU — Llama-2/3
  architecture. ``jax.checkpoint`` (remat) wraps each block when
  ``config.remat`` so activations are recomputed in backward.

No reference-code lineage: the reference (Ray) ships no transformer;
this exists so the framework's Train/Serve/Data stacks have a real
workload (reference analogue: python/ray/llm delegates models to vLLM).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attention_impl: str = "xla"  # "xla" | "flash" (pallas/blockwise)
    ce_impl: str = "xla"  # "xla" | "fused" (pallas lm-head CE; needs
    # B*S % 128 == 0, vocab % 128 == 0, no logit softcap)
    # logits softcap (Gemma-style) kept for generality; 0 disables.
    logit_softcap: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads


# Stock configs. Sources are the public architecture tables.
LLAMA_3_8B = LlamaConfig(
    vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    ffn_dim=14336, max_seq_len=8192, rope_theta=500000.0,
)
LLAMA_3_70B = LlamaConfig(
    vocab_size=128256, dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
    ffn_dim=28672, max_seq_len=8192, rope_theta=500000.0,
)
LLAMA_2_7B = LlamaConfig(
    vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=32,
    ffn_dim=11008, max_seq_len=4096, rope_theta=10000.0,
)
# Small configs for tests / benches / CI (CPU-mesh friendly).
LLAMA_TINY = LlamaConfig(
    vocab_size=512, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=256, max_seq_len=256, rope_theta=10000.0, remat=False,
)
LLAMA_BENCH = LlamaConfig(
    vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
    ffn_dim=5632, max_seq_len=2048, rope_theta=10000.0,
)


def param_specs(config: LlamaConfig) -> Dict[str, Any]:
    """PartitionSpec pytree matching init_params' structure.

    fsdp shards each matrix's embedding-like dim; model (TP) shards
    heads (qkv/o) and ffn hidden — the Megatron split, expressed
    declaratively and compiled by GSPMD.
    """
    return {
        "embed": P("model", "fsdp"),              # (V, D): vocab-sharded on TP
        "blocks": {
            "attn_norm": P(None, None),            # (L, D)
            "wq": P(None, "fsdp", "model", None),  # (L, D, H, hd)
            "wk": P(None, "fsdp", "model", None),  # (L, D, KVH, hd)
            "wv": P(None, "fsdp", "model", None),
            "wo": P(None, "model", None, "fsdp"),  # (L, H, hd, D)
            "mlp_norm": P(None, None),
            "w_gate": P(None, "fsdp", "model"),    # (L, D, F)
            "w_up": P(None, "fsdp", "model"),
            "w_down": P(None, "model", "fsdp"),    # (L, F, D)
        },
        "final_norm": P(None),                     # (D,)
        "lm_head": P("fsdp", "model"),             # (D, V)
    }


def make_dense_init(config: LlamaConfig):
    """Scaled-normal initializer in config.param_dtype (shared by the
    dense and MoE model families)."""

    def dense(key, shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(
            config.param_dtype
        )

    return dense


def init_attn_params(config: LlamaConfig, keys, dense) -> Dict[str, Any]:
    """Stacked attention sublayer params (norms + qkvo) — the shared
    half of both families' block params. keys: (k_q, k_k, k_v, k_o)."""
    c = config
    hd = c.head_dim
    L = c.n_layers
    k_q, k_k, k_v, k_o = keys
    return {
        "attn_norm": jnp.ones((L, c.dim), c.param_dtype),
        "wq": dense(k_q, (L, c.dim, c.n_heads, hd), c.dim),
        "wk": dense(k_k, (L, c.dim, c.n_kv_heads, hd), c.dim),
        "wv": dense(k_v, (L, c.dim, c.n_kv_heads, hd), c.dim),
        "wo": dense(k_o, (L, c.n_heads, hd, c.dim), c.n_heads * hd),
        "mlp_norm": jnp.ones((L, c.dim), c.param_dtype),
    }


def attn_param_count(config: LlamaConfig) -> int:
    """Per-layer params of the shared attention sublayer + both norms."""
    c = config
    return (
        2 * c.dim
        + c.dim * c.n_heads * c.head_dim
        + 2 * c.dim * c.n_kv_heads * c.head_dim
        + c.n_heads * c.head_dim * c.dim
    )


def init_params(rng: jax.Array, config: LlamaConfig) -> Dict[str, Any]:
    """Initialize parameters (stacked-layer layout, param_dtype)."""
    c = config
    k_embed, k_q, k_k, k_v, k_o, k_g, k_u, k_d, k_lm = jax.random.split(rng, 9)
    dense = make_dense_init(c)
    L = c.n_layers
    return {
        "embed": dense(k_embed, (c.vocab_size, c.dim), c.dim),
        "blocks": {
            **init_attn_params(c, (k_q, k_k, k_v, k_o), dense),
            "w_gate": dense(k_g, (L, c.dim, c.ffn_dim), c.dim),
            "w_up": dense(k_u, (L, c.dim, c.ffn_dim), c.dim),
            "w_down": dense(k_d, (L, c.ffn_dim, c.dim), c.ffn_dim),
        },
        "final_norm": jnp.ones((c.dim,), c.param_dtype),
        "lm_head": dense(k_lm, (c.dim, c.vocab_size), c.dim),
    }


def param_count(config: LlamaConfig) -> int:
    c = config
    per_layer = attn_param_count(c) + 3 * c.dim * c.ffn_dim
    return c.vocab_size * c.dim * 2 + c.n_layers * per_layer + c.dim


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(dt) * weight.astype(dt)


def rope_table(config: LlamaConfig, seq_len: int) -> Tuple[jax.Array, jax.Array]:
    hd = config.head_dim
    inv_freq = 1.0 / (
        config.rope_theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd)
    )
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (S, hd/2)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (B, S, H, hd); cos/sin: (S, hd/2) (or (B, S, hd/2) for shifted)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _attention_xla(q, k, v, config: LlamaConfig, *, causal: bool = True):
    """Grouped-query causal attention via einsum — fuses cleanly in XLA.

    q: (B, S, H, hd); k/v: (B, S, KVH, hd). Computed in fp32 logits.
    """
    B, S, H, hd = q.shape
    KVH = k.shape[2]
    G = H // KVH
    q = q.reshape(B, S, KVH, G, hd)
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bskgh,btkh->bkgst", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        logits = jnp.where(mask[None, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgst,btkh->bskgh", probs, v)
    return out.reshape(B, S, H, hd)


def _attention_ring(q, k, v, config: LlamaConfig):
    """Sequence-parallel attention: activations sharded (batch on
    data/fsdp, sequence on seq); the ring runs inside shard_map against
    the ambient mesh, rotating KV shards over ICI. Falls back to flash
    when there is no ambient mesh or the seq axis is trivial."""
    from jax.sharding import get_abstract_mesh

    from ray_tpu.ops.attention import flash_attention
    from ray_tpu.ops.ring_attention import ring_attention

    mesh = get_abstract_mesh()
    if mesh is None or mesh.empty or dict(mesh.shape).get("seq", 1) == 1:
        return flash_attention(q, k, v, causal=True)
    # keep heads sharded over the TP axis inside the ring (qkv arrive
    # head-sharded from the model-split projections; replicating them
    # here would duplicate the whole ring per TP rank)
    tp = dict(mesh.shape).get("model", 1)
    kvh = k.shape[2]
    head_axis = "model" if (kvh % tp == 0 and q.shape[2] % tp == 0) else None
    spec = P(("data", "fsdp"), "seq", head_axis, None)
    return jax.shard_map(
        partial(ring_attention, axis_name="seq"),
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def _attention(q, k, v, config: LlamaConfig):
    if config.attention_impl == "flash":
        from ray_tpu.ops.attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    if config.attention_impl == "ring":
        return _attention_ring(q, k, v, config)
    if config.attention_impl != "xla":
        raise ValueError(
            f"unknown attention_impl {config.attention_impl!r}; "
            "expected 'xla', 'flash', or 'ring' (sequence parallel)"
        )
    return _attention_xla(q, k, v, config)


def attention_sublayer(config: LlamaConfig, x: jax.Array,
                       layer: Dict[str, jax.Array],
                       cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Pre-norm GQA attention + residual (shared by the dense and MoE
    model families — fix attention once, both models follow)."""
    c = config
    h = rms_norm(x, layer["attn_norm"], c.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(c.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(c.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(c.dtype))
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = _attention(q, k, v, c)
    return x + jnp.einsum("bshk,hkd->bsd", attn, layer["wo"].astype(c.dtype))


def block_fn(config: LlamaConfig, x: jax.Array, layer: Dict[str, jax.Array],
             cos: jax.Array, sin: jax.Array) -> jax.Array:
    """One transformer block. x: (B, S, D) in config.dtype."""
    c = config
    x = attention_sublayer(c, x, layer, cos, sin)

    h = rms_norm(x, layer["mlp_norm"], c.norm_eps)
    gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(c.dtype))
    up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(c.dtype))
    x = x + jnp.einsum(
        "bsf,fd->bsd", jax.nn.silu(gate) * up, layer["w_down"].astype(c.dtype)
    )
    return x


def forward_hidden(params: Dict[str, Any], tokens: jax.Array,
                   config: LlamaConfig) -> jax.Array:
    """tokens (B, S) int32 → final-norm hidden states (B, S, D) in
    config.dtype (everything except the lm-head projection)."""
    c = config
    B, S = tokens.shape
    x = params["embed"].astype(c.dtype)[tokens]
    cos, sin = rope_table(c, S)

    blk = partial(block_fn, c)
    if c.remat:
        blk = jax.checkpoint(
            blk, policy=jax.checkpoint_policies.nothing_saveable
        )

    def scan_body(carry, layer):
        return blk(carry, layer, cos, sin), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    return rms_norm(x, params["final_norm"], c.norm_eps)


def forward(params: Dict[str, Any], tokens: jax.Array,
            config: LlamaConfig) -> jax.Array:
    """tokens (B, S) int32 → logits (B, S, V) float32.

    Layers run under lax.scan over the stacked-params leading axis;
    each iteration optionally rematerialized.
    """
    c = config
    x = forward_hidden(params, tokens, c)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"].astype(c.dtype))
    logits = logits.astype(jnp.float32)
    if c.logit_softcap:
        logits = jnp.tanh(logits / c.logit_softcap) * c.logit_softcap
    return logits


def unpack_batch(batch: Dict[str, jax.Array]):
    """batch {"tokens": (B, S+1)} or {"inputs","targets"} [+"mask"]
    -> (inputs, targets, mask) — shared by both model families."""
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
        mask = batch.get("mask")
        if mask is not None:
            mask = mask[:, 1:]
        return inputs, targets, mask
    return batch["inputs"], batch["targets"], batch.get("mask")


def masked_mean(nll: jax.Array, mask) -> jax.Array:
    """Masked-mean reduction shared by every CE path."""
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def masked_ce(logits: jax.Array, targets: jax.Array, mask) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return masked_mean(nll, mask)


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            config: LlamaConfig) -> jax.Array:
    """Next-token cross entropy. batch: {"tokens": (B, S+1) int32} or
    {"inputs": (B,S), "targets": (B,S)} with optional "mask".

    ce_impl="fused" routes the lm-head projection + softmax-CE through
    the Pallas kernel (ops/pallas_ce.py): fp32 logits never touch HBM.
    """
    c = config
    inputs, targets, mask = unpack_batch(batch)
    B, S = inputs.shape
    if c.ce_impl == "fused":
        # an explicit "fused" request that can't be honored must FAIL,
        # not silently run XLA — a fused-kernel benchmark or live-chip
        # validation would otherwise measure the wrong implementation
        problems = []
        if c.logit_softcap:
            problems.append("logit_softcap is set")
        if (B * S) % 128 != 0:
            problems.append(f"B*S={B * S} not a multiple of 128")
        if c.vocab_size % 128 != 0:
            problems.append(f"vocab_size={c.vocab_size} not a multiple of 128")
        if problems:
            raise ValueError(
                "ce_impl='fused' not applicable: " + "; ".join(problems)
            )
        from ray_tpu.ops.pallas_ce import fused_cross_entropy

        x = forward_hidden(params, inputs, c)
        nll = fused_cross_entropy(
            x.reshape(B * S, c.dim),
            params["lm_head"].astype(c.dtype),
            targets.reshape(B * S),
        ).reshape(B, S)
        return masked_mean(nll, mask)
    logits = forward(params, inputs, c)
    return masked_ce(logits, targets, mask)


# ---------------------------------------------------------------------
# KV-cache inference path (used by ray_tpu.llm — reference analogue:
# python/ray/llm delegates generation to vLLM; here generation is
# in-tree and XLA-shaped: static cache shapes, dynamic_update_slice
# writes, length-masked attention, one jitted program per bucket).
# ---------------------------------------------------------------------

def init_kv_cache(config: LlamaConfig, batch: int, max_seq: int):
    """Preallocated cache: k/v (L, B, max_seq, KVH, hd) in config.dtype."""
    c = config
    shape = (c.n_layers, batch, max_seq, c.n_kv_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
    }


def _attention_cached(q, k_cache, v_cache, pos, config: LlamaConfig):
    """q (B, T, H, hd) new queries at absolute positions ``pos`` (B, T);
    k/v_cache (B, S, KVH, hd) hold all tokens written so far (including
    the new ones). Rows attend to cache slots <= their position."""
    B, T, H, hd = q.shape
    S = k_cache.shape[1]
    KVH = k_cache.shape[2]
    G = H // KVH
    qg = q.reshape(B, T, KVH, G, hd)
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum(
        "btkgh,bskh->bkgts", qg, k_cache,
        preferred_element_type=jnp.float32,
    ) * scale
    mask = jnp.arange(S)[None, None, :] <= pos[:, :, None]  # (B, T, S)
    logits = jnp.where(mask[:, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgts,bskh->btkgh", probs, v_cache)
    return out.reshape(B, T, H, hd)


def forward_with_cache(
    params: Dict[str, Any],
    tokens: jax.Array,
    cache: Dict[str, jax.Array],
    start_pos: jax.Array,
    config: LlamaConfig,
):
    """Incremental forward: tokens (B, T) appended at per-sequence
    offsets ``start_pos`` (B,). Returns (logits (B, T, V) fp32, updated
    cache). T is static (bucketed by the engine); start_pos is traced.
    """
    c = config
    B, T = tokens.shape
    max_seq = cache["k"].shape[2]
    x = params["embed"].astype(c.dtype)[tokens]
    cos_full, sin_full = rope_table(c, max_seq)
    pos = start_pos[:, None] + jnp.arange(T)[None, :]          # (B, T)
    cos = cos_full[pos]                                         # (B, T, hd/2)
    sin = sin_full[pos]

    def body(x, layer_and_cache):
        layer, k_c, v_c = layer_and_cache
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(c.dtype))
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(c.dtype))
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(c.dtype))
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        # scatter the T new k/v rows into each sequence's slot range
        def write(cache_b, new_b, start_b):
            return jax.lax.dynamic_update_slice(
                cache_b, new_b.astype(cache_b.dtype), (start_b, 0, 0)
            )

        k_c = jax.vmap(write)(k_c, k, start_pos)
        v_c = jax.vmap(write)(v_c, v, start_pos)
        attn = _attention_cached(q, k_c, v_c, pos, c)
        x = x + jnp.einsum("bshk,hkd->bsd", attn, layer["wo"].astype(c.dtype))
        h = rms_norm(x, layer["mlp_norm"], c.norm_eps)
        gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(c.dtype))
        up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(c.dtype))
        x = x + jnp.einsum(
            "bsf,fd->bsd", jax.nn.silu(gate) * up, layer["w_down"].astype(c.dtype)
        )
        return x, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"].astype(c.dtype))
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """Approx training FLOPs/token: 6*N matmul + attention term."""
    n = param_count(config) - config.vocab_size * config.dim  # non-embed approx
    attn = 12 * config.n_layers * config.dim * seq_len  # 2*2*3 * L * D * S
    return 6.0 * n + attn
