"""Memory-efficient causal attention (flash-attention algorithm).

Online-softmax blockwise attention: O(S) memory instead of the O(S^2)
logits tensor. Two code paths behind one signature:

- ``flash_attention`` — blockwise `lax.scan` formulation that XLA fuses
  well on any backend (and is the CPU-mesh test path).
- A Pallas TPU kernel (ray_tpu.ops.pallas_attention) is substituted on
  TPU when available; same semantics, hand-tiled for MXU/VMEM.

Supports GQA (n_kv_heads divides n_heads). Layout: q (B, S, H, hd),
k/v (B, T, KVH, hd) — the layout ray_tpu.models uses.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def _blockwise_attention(q, k, v, *, causal: bool, block_q: int, block_kv: int,
                         q_offset: int = 0, kv_offset: int = 0):
    """Core online-softmax loop. Shapes:
    q (B, Sq, KVH, G, hd), k/v (B, Skv, KVH, hd). fp32 accumulation.
    ``q_offset``/``kv_offset`` are absolute position offsets (used by
    ring attention, where each shard holds a slice of the sequence).
    """
    B, Sq, KVH, G, hd = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(hd)
    nq = max(1, Sq // block_q)
    nkv = max(1, Skv // block_kv)
    block_q = Sq // nq
    block_kv = Skv // nkv

    qb = q.reshape(B, nq, block_q, KVH, G, hd)
    kb = k.reshape(B, nkv, block_kv, KVH, hd)
    vb = v.reshape(B, nkv, block_kv, KVH, hd)

    q_pos = q_offset + jnp.arange(Sq).reshape(nq, block_q)
    kv_pos = kv_offset + jnp.arange(Skv).reshape(nkv, block_kv)

    def per_qblock(qi, q_blk):
        # q_blk: (B, block_q, KVH, G, hd)
        acc0 = jnp.zeros((B, block_q, KVH, G, hd), jnp.float32)
        m0 = jnp.full((B, block_q, KVH, G), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((B, block_q, KVH, G), jnp.float32)

        def body(carry, inputs):
            acc, m, l = carry
            ki, k_blk, v_blk = inputs
            logits = jnp.einsum(
                "bqkgh,btkh->bqkgt", q_blk, k_blk,
                preferred_element_type=jnp.float32,
            ) * scale
            if causal:
                mask = q_pos[qi][:, None] >= kv_pos[ki][None, :]
                logits = jnp.where(mask[None, :, None, None, :], logits, -1e30)
            m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
            p = jnp.exp(logits - m_new[..., None])
            corr = jnp.exp(m - m_new)
            l = l * corr + jnp.sum(p, axis=-1)
            pv = jnp.einsum(
                "bqkgt,btkh->bqkgh", p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32,
            )
            acc = acc * corr[..., None] + pv
            return (acc, m_new, l), None

        (acc, m, l), _ = jax.lax.scan(
            body, (acc0, m0, l0),
            (jnp.arange(nkv), jnp.moveaxis(kb, 1, 0), jnp.moveaxis(vb, 1, 0)),
        )
        return acc / jnp.maximum(l, 1e-30)[..., None]

    out = jax.lax.map(
        lambda args: per_qblock(*args),
        (jnp.arange(nq), jnp.moveaxis(qb, 1, 0)),
    )  # (nq, B, block_q, KVH, G, hd)
    out = jnp.moveaxis(out, 0, 1).reshape(B, Sq, KVH, G, hd)
    return out


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 512,
    block_kv: int = 512,
) -> jax.Array:
    """q (B, S, H, hd); k/v (B, T, KVH, hd) → (B, S, H, hd).

    On TPU, dispatches to the Pallas kernel when the shapes are
    tile-friendly; otherwise runs the XLA blockwise formulation.
    """
    B, S, H, hd = q.shape
    KVH = k.shape[2]
    if H % KVH != 0:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads {KVH}")
    G = H // KVH

    # Trace-safe backend probe (q may be a tracer inside jit).
    if jax.default_backend() in ("tpu", "axon"):
        try:
            from .pallas_attention import pallas_flash_attention

            return pallas_flash_attention(q, k, v, causal=causal)
        except (ImportError, NotImplementedError):
            pass

    qg = q.reshape(B, S, KVH, G, hd)
    out = _blockwise_attention(
        qg, k, v, causal=causal, block_q=block_q, block_kv=block_kv
    )
    return out.reshape(B, S, H, hd).astype(q.dtype)
