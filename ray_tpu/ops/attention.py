"""Memory-efficient causal attention (flash-attention algorithm).

Online-softmax blockwise attention: O(S) memory instead of the O(S^2)
logits tensor. Three consumers share the core accumulate step:

- ``flash_attention`` — single-device blockwise `lax.scan` formulation
  that XLA fuses well on any backend (the CPU-mesh test path).
- ``ray_tpu.ops.ring_attention`` — sequence-parallel ring schedule that
  feeds successive KV shards through the same accumulator.
- A Pallas TPU kernel (ray_tpu.ops.pallas_attention) is substituted on
  TPU when available; same semantics, hand-tiled for MXU/VMEM.

Supports GQA (n_kv_heads divides n_heads). Layout: q (B, S, H, hd),
k/v (B, T, KVH, hd) — the layout ray_tpu.models uses.

Reference parity note: the reference has NO sequence-parallel or
flash-attention code (SURVEY.md §5.7 — delegated to vLLM/torch); this
is TPU-native net-new capability.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _blockwise_accum(
    q, k, v, acc, m, l, *, causal: bool, block_q: int, block_kv: int,
    q_offset=0, kv_offset=0,
):
    """Accumulate attention of q against one K/V span into running
    online-softmax state. Shapes: q (B, Sq, KVH, G, hd), k/v
    (B, Skv, KVH, hd); acc (B, Sq, KVH, G, hd) f32, m/l (B, Sq, KVH, G)
    f32. ``q_offset``/``kv_offset`` may be tracers (ring attention
    passes the rotating shard's absolute position).

    Returns updated (acc, m, l). Fully-masked blocks are exact no-ops:
    masked probabilities are explicitly zeroed (relying on exp(-big)
    underflow is wrong when a block is masked BEFORE any visible block
    has set a finite running max).
    """
    B, Sq, KVH, G, hd = q.shape
    Skv = k.shape[1]
    scale = 1.0 / math.sqrt(hd)
    nq = max(1, Sq // block_q)
    nkv = max(1, Skv // block_kv)
    block_q = Sq // nq
    block_kv = Skv // nkv

    qb = q.reshape(B, nq, block_q, KVH, G, hd)
    kb = k.reshape(B, nkv, block_kv, KVH, hd)
    vb = v.reshape(B, nkv, block_kv, KVH, hd)
    accb = acc.reshape(B, nq, block_q, KVH, G, hd)
    mb = m.reshape(B, nq, block_q, KVH, G)
    lb = l.reshape(B, nq, block_q, KVH, G)

    q_pos = q_offset + jnp.arange(Sq).reshape(nq, block_q)
    kv_pos = kv_offset + jnp.arange(Skv).reshape(nkv, block_kv)

    def per_qblock(args):
        qi, q_blk, acc0, m0, l0 = args

        def body(carry, inputs):
            acc, m, l = carry
            ki, k_blk, v_blk = inputs
            logits = jnp.einsum(
                "bqkgh,btkh->bqkgt", q_blk, k_blk,
                preferred_element_type=jnp.float32,
            ) * scale
            if causal:
                mask = q_pos[qi][:, None] >= kv_pos[ki][None, :]
                logits = jnp.where(mask[None, :, None, None, :], logits, _NEG_INF)
            blk_max = jnp.max(logits, axis=-1)
            m_new = jnp.maximum(m, blk_max)
            # clamp for exp() only — fully-masked rows keep m_new=-inf
            # in the carry but compute with 0 to avoid inf/nan
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(logits - m_safe[..., None])
            if causal:
                p = jnp.where(mask[None, :, None, None, :], p, 0.0)
            corr = jnp.where(
                jnp.isfinite(m), jnp.exp(m - m_safe), 0.0
            )
            l = l * corr + jnp.sum(p, axis=-1)
            pv = jnp.einsum(
                "bqkgt,btkh->bqkgh", p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32,
            )
            acc = acc * corr[..., None] + pv
            return (acc, m_new, l), None

        (acc, m, l), _ = jax.lax.scan(
            body, (acc0, m0, l0),
            (jnp.arange(nkv), jnp.moveaxis(kb, 1, 0), jnp.moveaxis(vb, 1, 0)),
        )
        return acc, m, l

    out = jax.lax.map(
        per_qblock,
        (
            jnp.arange(nq),
            jnp.moveaxis(qb, 1, 0),
            jnp.moveaxis(accb, 1, 0),
            jnp.moveaxis(mb, 1, 0),
            jnp.moveaxis(lb, 1, 0),
        ),
    )
    acc2, m2, l2 = (jnp.moveaxis(t, 0, 1) for t in out)
    return (
        acc2.reshape(B, Sq, KVH, G, hd),
        m2.reshape(B, Sq, KVH, G),
        l2.reshape(B, Sq, KVH, G),
    )


def init_attention_state(B, Sq, KVH, G, hd):
    return (
        jnp.zeros((B, Sq, KVH, G, hd), jnp.float32),
        jnp.full((B, Sq, KVH, G), -jnp.inf, jnp.float32),
        jnp.zeros((B, Sq, KVH, G), jnp.float32),
    )


def finalize_attention_state(acc, l):
    return acc / jnp.maximum(l, 1e-30)[..., None]


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 512,
    block_kv: int = 512,
) -> jax.Array:
    """q (B, S, H, hd); k/v (B, T, KVH, hd) → (B, S, H, hd).

    On TPU, dispatches to the Pallas kernel when the shapes are
    tile-friendly; otherwise runs the XLA blockwise formulation.
    """
    B, S, H, hd = q.shape
    KVH = k.shape[2]
    if H % KVH != 0:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads {KVH}")
    G = H // KVH

    # Trace-safe backend probe (q may be a tracer inside jit).
    if jax.default_backend() in ("tpu", "axon"):
        try:
            from .pallas_attention import pallas_flash_attention

            return pallas_flash_attention(q, k, v, causal=causal)
        except (ImportError, NotImplementedError):
            pass

    qg = q.reshape(B, S, KVH, G, hd)
    acc, m, l = init_attention_state(B, S, KVH, G, hd)
    acc, m, l = _blockwise_accum(
        qg, k, v, acc, m, l, causal=causal, block_q=block_q, block_kv=block_kv
    )
    out = finalize_attention_state(acc, l)
    return out.reshape(B, S, H, hd).astype(q.dtype)
