"""Pallas TPU flash-attention kernel (forward + FlashAttention-2 backward).

Hand-tiled MXU implementation of the online-softmax attention in
``ray_tpu.ops.attention`` — same semantics (causal, GQA), O(S) memory,
logits never materialized in HBM. ``ops.attention.flash_attention``
substitutes this kernel on TPU backends; the XLA blockwise formulation
remains the fallback (and the numerical reference in
tests/test_pallas_attention.py).

Reference parity note: the reference (Ray) has no attention kernels at
all (SURVEY.md §5.7 — delegated to vLLM/torch); this is TPU-native
net-new capability, required to hit the BASELINE.md MFU bar.

Layout contract (matches ray_tpu.models):
    q (B, S, H, hd); k/v (B, T, KVH, hd), H = G * KVH.
Internally transposed to head-major (B, H, S, hd) so the kernel tiles
(S, hd) blocks onto the MXU with hd on the 128-lane axis.

Design notes:
- Grid (B, H, q_blocks, kv_blocks), kv innermost and "arbitrary"; the
  online-softmax state (m, l, acc) lives in VMEM scratch carried across
  kv steps; output written once on each row's last visible kv block.
- Causal blocks strictly above the diagonal are skipped with pl.when —
  ~2x fewer MXU ops at long seq, same skip the backward kernels use.
- Backward follows FlashAttention-2: saved (o, lse) + recomputed p per
  tile; dkv kernel accumulates over q blocks, dq kernel over kv blocks.
  GQA group-summing of dk/dv happens outside the kernel (per-q-head
  partials), trading a small HBM buffer for race-free accumulation.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_NEG_INF = float("-inf")


def _interpret() -> bool:
    # CPU has no Mosaic; interpret mode keeps the kernel testable on the
    # virtual device mesh.
    return jax.default_backend() == "cpu"


def _pick_block(size: int, preferred: int) -> int:
    for b in (preferred, 512, 256, 128):
        if b <= preferred and size % b == 0:
            return b
    raise NotImplementedError(f"sequence length {size} not a multiple of 128")


def _check_shapes(q, k, v):
    B, S, H, hd = q.shape
    Bk, T, KVH, hdk = k.shape
    if (B, T, KVH, hdk) != k.shape or k.shape != v.shape:
        raise NotImplementedError("k/v shape mismatch")
    if Bk != B or hdk != hd:
        raise NotImplementedError("q/k shape mismatch")
    if H % KVH != 0:
        raise NotImplementedError(f"H={H} not divisible by KVH={KVH}")
    if hd % _LANES != 0:
        raise NotImplementedError(
            f"head_dim={hd} not a multiple of {_LANES} (MXU lane width)"
        )
    return B, S, H, hd, T, KVH


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_kv, nk):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    q_start = qi * block_q
    kv_start = ki * block_kv

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: a block strictly above the diagonal contributes nothing
    visible = (q_start + block_q - 1 >= kv_start) if causal else True

    @pl.when(visible)
    def _accumulate():
        q = q_ref[0, 0]                       # (block_q, hd)
        k = k_ref[0, 0]                       # (block_kv, hd)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                             # (block_q, block_kv) f32
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = kv_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_ref[:]                     # (block_q, LANES)
        blk_max = jnp.max(s, axis=1, keepdims=True)      # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(blk_max, m_prev.shape))
        # rows with nothing visible yet: compute exp against 0, carry -inf
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, :1])        # masked cols: exp(-inf)=0
        corr = jnp.where(
            jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_ref[:] = l_ref[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), corr.shape)
        acc_ref[:] = acc_ref[:] * corr[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    # last kv block whose columns any row of this q block can see
    if causal:
        last_ki = jnp.minimum(nk - 1, (q_start + block_q - 1) // block_kv)
    else:
        last_ki = nk - 1

    @pl.when(ki == last_ki)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0, 0] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # lane-broadcast (bq, LANES) layout — Mosaic requires the last
        # two block dims to tile (8, 128), so scalar-per-row stats ride
        # a full lane vector (same layout the stock jax kernel uses)
        lse_ref[0, 0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _fwd(q, k, v, causal, block_q, block_kv):
    """q (B,H,S,hd), k/v (B,KVH,T,hd) -> o (B,H,S,hd), lse (B,H,S) f32."""
    B, H, S, hd = q.shape
    KVH, T = k.shape[1], k.shape[2]
    G = H // KVH
    bq = _pick_block(S, block_q)
    bkv = _pick_block(T, block_kv)
    nq, nk = S // bq, T // bkv
    scale = 1.0 / math.sqrt(hd)

    grid = (B, H, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=bq, block_kv=bkv, nk=nk,
    )
    flops_per_bh = 4 * S * T * hd * (0.5 if causal else 1.0)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bkv, hd), lambda b, h, i, j: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, bkv, hd), lambda b, h, i, j: (b, h // G, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, hd), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(B * H * flops_per_bh),
            bytes_accessed=int(
                q.size * q.dtype.itemsize + 2 * k.size * k.dtype.itemsize
                + q.size * q.dtype.itemsize),
            transcendentals=int(B * H * S * T * (0.5 if causal else 1.0)),
        ),
        interpret=_interpret(),
        name="flash_attention_fwd",
    )(q, k, v)
    return o, lse


# ----------------------------------------------------------------------
# backward (FlashAttention-2)
# ----------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, block_q, block_kv, nq):
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    q_start = qi * block_q
    kv_start = ki * block_kv

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    visible = (q_start + block_q - 1 >= kv_start) if causal else True

    @pl.when(visible)
    def _accumulate():
        q = q_ref[0, 0]                       # (block_q, hd)
        k = k_ref[0, 0]                       # (block_kv, hd)
        v = v_ref[0, 0]
        do = do_ref[0, 0]                     # (block_q, hd)
        lse = lse_ref[0, 0][:, :1]            # (block_q, 1)
        delta = delta_ref[0, 0][:, :1]        # (block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = kv_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                  # (block_q, block_kv)
        # dv += p^T @ do
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dp = do @ v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc,
                   *, scale, causal, block_q, block_kv, nk):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    q_start = qi * block_q
    kv_start = ki * block_kv

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    visible = (q_start + block_q - 1 >= kv_start) if causal else True

    @pl.when(visible)
    def _accumulate():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = kv_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        last_ki = jnp.minimum(nk - 1, (q_start + block_q - 1) // block_kv)
    else:
        last_ki = nk - 1

    @pl.when(ki == last_ki)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, block_q, block_kv):
    B, H, S, hd = q.shape
    KVH, T = k.shape[1], k.shape[2]
    G = H // KVH
    bq = _pick_block(S, block_q)
    bkv = _pick_block(T, block_kv)
    nq, nk = S // bq, T // bkv
    scale = 1.0 / math.sqrt(hd)

    # delta_i = rowsum(dO_i * O_i) — cheap elementwise reduce, XLA
    # fuses it; lane-broadcast to match the lse layout
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                keepdims=True),
        lse.shape,
    )

    common_in_specs = [
        pl.BlockSpec((1, 1, bq, hd), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bkv, hd), lambda b, h, j, i: (b, h // G, j, 0)),
        pl.BlockSpec((1, 1, bkv, hd), lambda b, h, j, i: (b, h // G, j, 0)),
        pl.BlockSpec((1, 1, bq, hd), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, _LANES), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, _LANES), lambda b, h, j, i: (b, h, i, 0)),
    ]
    # dk/dv accumulated per q-head (B, H, T, hd); summed over the GQA
    # group below — keeps the kernel write sets disjoint
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=bq, block_kv=bkv, nq=nq,
        ),
        grid=(B, H, nk, nq),
        in_specs=common_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bkv, hd), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bkv, hd), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, hd), k.dtype),
            jax.ShapeDtypeStruct((B, H, T, hd), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, hd), jnp.float32),
            pltpu.VMEM((bkv, hd), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=_interpret(),
        name="flash_attention_bwd_dkv",
    )(q, k, v, do, lse, delta)
    if G > 1:
        dk = dk_h.reshape(B, KVH, G, T, hd).sum(axis=2).astype(k.dtype)
        dv = dv_h.reshape(B, KVH, G, T, hd).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_h, dv_h

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=bq, block_kv=bkv, nk=nk,
        ),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bkv, hd), lambda b, h, i, j: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, bkv, hd), lambda b, h, i, j: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, hd), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, hd), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=_interpret(),
        name="flash_attention_bwd_dq",
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_kv):
    o, _ = _fwd(q, k, v, causal, block_q, block_kv)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_kv):
    o, lse = _fwd(q, k, v, causal, block_q, block_kv)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_kv, res, do):
    q, k, v, o, lse = res
    return _bwd(q, k, v, o, lse, do, causal, block_q, block_kv)


_flash.defvjp(_flash_fwd, _flash_bwd)


def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    *,
    block_q: int = 512,
    block_kv: int = 512,
) -> jax.Array:
    """Flash attention on TPU via Pallas. q (B,S,H,hd), k/v (B,T,KVH,hd)
    -> (B,S,H,hd). Raises NotImplementedError for shapes the kernel does
    not tile (caller falls back to the XLA blockwise path)."""
    B, S, H, hd, T, KVH = _check_shapes(q, k, v)
    _pick_block(S, block_q)
    _pick_block(T, block_kv)
    qt = q.transpose(0, 2, 1, 3)          # (B, H, S, hd)
    kt = k.transpose(0, 2, 1, 3)          # (B, KVH, T, hd)
    vt = v.transpose(0, 2, 1, 3)
    o = _flash(qt, kt, vt, causal, block_q, block_kv)
    return o.transpose(0, 2, 1, 3)
