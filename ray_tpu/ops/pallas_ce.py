"""Pallas TPU fused lm-head cross-entropy (forward + custom VJP).

The final-projection loss is the classic HBM hog: XLA materializes
(N, V) fp32 logits (N = B*S tokens, V = vocab) for softmax-CE — at
N=16k, V=128k that's an 8 GiB round trip per step. This kernel fuses
x @ W with an online logsumexp over vocab tiles, so only (N,) outputs
(lse, target logit) ever leave VMEM; the backward recomputes each
logits tile (one extra matmul each for dx and dW — FLOPs for
bandwidth, the flash-attention trade).

Reference parity note: the reference (Ray) ships no kernels (losses are
torch's, downstream); this is TPU-native net-new, same role as
ops/pallas_attention.py for the MFU bar.

Contract:
    x (N, D) bf16/f32, w (D, V), targets (N,) int32
    -> per-token losses (N,) f32 = lse_i - logit_i[target_i]
Masking/averaging stay with the caller (models.llama.masked_ce shape).
N must divide by the row block (128), V by the vocab block (512|256|128),
D is kept whole (fits VMEM alongside one vocab tile in bf16 for
D <= 8192).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_NEG_INF = float("-inf")


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _pick_block(size: int, preferred: int) -> int:
    for b in (preferred, 512, 256, 128):
        if b <= preferred and size % b == 0:
            return b
    raise NotImplementedError(f"dimension {size} not a multiple of 128")


# ----------------------------------------------------------------------
# forward: online logsumexp over vocab tiles + target-logit gather
# ----------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, t_ref, lse_ref, tgt_ref, m_ref, l_ref, g_ref,
                *, block_n, block_v, nv):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        g_ref[:] = jnp.zeros_like(g_ref)

    x = x_ref[:]                                   # (block_n, D)
    w = w_ref[:]                                   # (D, block_v)
    s = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                              # (block_n, block_v) f32

    # online logsumexp
    m_prev = m_ref[:]                              # (block_n, LANES)
    blk_max = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, jnp.broadcast_to(blk_max, m_prev.shape))
    p_sum = jnp.sum(jnp.exp(s - m_new[:, :1]), axis=1, keepdims=True)
    corr = jnp.exp(m_prev - m_new)
    l_ref[:] = l_ref[:] * corr + jnp.broadcast_to(p_sum, corr.shape)
    m_ref[:] = m_new

    # target logit: the one column (if any) matching this tile
    t = t_ref[:]                                   # (block_n, 1) int32
    cols = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    hit = cols == t                                # (block_n, block_v)
    g_ref[:] = g_ref[:] + jnp.broadcast_to(
        jnp.sum(jnp.where(hit, s, 0.0), axis=1, keepdims=True),
        g_ref.shape,
    )

    @pl.when(vi == nv - 1)
    def _finish():
        lse = m_ref[:, :1] + jnp.log(l_ref[:, :1])
        lse_ref[:] = lse[:, 0]
        tgt_ref[:] = g_ref[:, 0]


def _fwd_call(x, w, targets, block_n, block_v):
    N, D = x.shape
    V = w.shape[1]
    if N % block_n != 0:
        # silent floor-division here would drop tail rows
        raise NotImplementedError(
            f"N={N} not a multiple of the row block ({block_n}); pad the "
            "token dimension"
        )
    nv = V // block_v
    grid = (N // block_n, nv)
    kernel = functools.partial(
        _fwd_kernel, block_n=block_n, block_v=block_v, nv=nv
    )
    lse, tgt = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, D), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((D, block_v), lambda ni, vi: (0, vi)),
            pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n,), lambda ni, vi: (ni,)),
            pl.BlockSpec((block_n,), lambda ni, vi: (ni,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N,), jnp.float32),
            jax.ShapeDtypeStruct((N,), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # target logit
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(x, w, targets[:, None].astype(jnp.int32))
    return lse, tgt


# ----------------------------------------------------------------------
# backward: recompute each logits tile; dlogits = (softmax - onehot) * g
# ----------------------------------------------------------------------

def _dx_kernel(x_ref, w_ref, t_ref, lse_ref, gin_ref, dx_ref, acc_ref,
               *, block_n, block_v, nv):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]
    w = w_ref[:]                                   # (D, block_v)
    s = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    p = jnp.exp(s - lse_ref[:][:, None])           # softmax tile
    t = t_ref[:]
    cols = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    dlog = (p - jnp.where(cols == t, 1.0, 0.0)) * gin_ref[:][:, None]
    acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
        dlog.astype(w.dtype), w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                              # (block_n, D)

    @pl.when(vi == nv - 1)
    def _finish():
        dx_ref[:] = acc_ref[:].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, t_ref, lse_ref, gin_ref, dw_ref, acc_ref,
               *, block_n, block_v, nn):
    ni = pl.program_id(1)

    @pl.when(ni == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]                                   # (block_n, D)
    w = w_ref[:]                                   # (D, block_v)
    s = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    p = jnp.exp(s - lse_ref[:][:, None])
    t = t_ref[:]
    vi = pl.program_id(0)
    cols = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], s.shape[1]), 1)
    dlog = (p - jnp.where(cols == t, 1.0, 0.0)) * gin_ref[:][:, None]
    acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
        x, dlog.astype(x.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                              # (D, block_v)

    @pl.when(ni == nn - 1)
    def _finish():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)


def _bwd_call(x, w, targets, lse, g, block_n, block_v):
    N, D = x.shape
    V = w.shape[1]
    nv = V // block_v
    nn = N // block_n
    t2 = targets[:, None].astype(jnp.int32)

    dx = pl.pallas_call(
        functools.partial(
            _dx_kernel, block_n=block_n, block_v=block_v, nv=nv
        ),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((block_n, D), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((D, block_v), lambda ni, vi: (0, vi)),
            pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((block_n,), lambda ni, vi: (ni,)),
            pl.BlockSpec((block_n,), lambda ni, vi: (ni,)),
        ],
        out_specs=pl.BlockSpec((block_n, D), lambda ni, vi: (ni, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(x, w, t2, lse, g)

    dw = pl.pallas_call(
        functools.partial(
            _dw_kernel, block_n=block_n, block_v=block_v, nn=nn
        ),
        grid=(nv, nn),
        in_specs=[
            pl.BlockSpec((block_n, D), lambda vi, ni: (ni, 0)),
            pl.BlockSpec((D, block_v), lambda vi, ni: (0, vi)),
            pl.BlockSpec((block_n, 1), lambda vi, ni: (ni, 0)),
            pl.BlockSpec((block_n,), lambda vi, ni: (ni,)),
            pl.BlockSpec((block_n,), lambda vi, ni: (ni,)),
        ],
        out_specs=pl.BlockSpec((D, block_v), lambda vi, ni: (0, vi)),
        out_shape=jax.ShapeDtypeStruct((D, V), w.dtype),
        scratch_shapes=[pltpu.VMEM((D, block_v), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(x, w, t2, lse, g)
    return dx, dw


# ----------------------------------------------------------------------
# public API with custom VJP
# ----------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_cross_entropy(x, w, targets, block_n: int = 128,
                        block_v: int = 512):
    """Per-token losses (N,) f32 for logits = x @ w against targets.

    Out-of-range targets are clamped into [0, V) to match the XLA
    path's gather semantics (jnp.take_along_axis clamps under jit);
    without the clamp the kernel's one-hot match would silently miss
    and return lse instead of a real loss."""
    targets = jnp.clip(targets, 0, w.shape[1] - 1)
    lse, tgt = _fwd_call(x, w, targets, block_n, _pick_block(w.shape[1], block_v))
    return lse - tgt


def _vjp_fwd(x, w, targets, block_n, block_v):
    targets = jnp.clip(targets, 0, w.shape[1] - 1)  # match XLA gather clamp
    bv = _pick_block(w.shape[1], block_v)
    lse, tgt = _fwd_call(x, w, targets, block_n, bv)
    return lse - tgt, (x, w, targets, lse)


def _vjp_bwd(block_n, block_v, res, g):
    x, w, targets, lse = res
    bv = _pick_block(w.shape[1], block_v)
    dx, dw = _bwd_call(x, w, targets, lse, g.astype(jnp.float32),
                       block_n, bv)
    return dx, dw, None


fused_cross_entropy.defvjp(_vjp_fwd, _vjp_bwd)


def xla_cross_entropy(x, w, targets):
    """Reference path: materialized logits + log_softmax (what XLA does
    for models.llama.loss_fn today)."""
    logits = (x @ w.astype(x.dtype)).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(
        logp, targets[:, None].astype(jnp.int32), axis=1
    )[:, 0]
