"""TPU compute kernels: flash/ring attention, MoE dispatch, collective
helpers. XLA blockwise fallbacks keep every op runnable on the CPU test
mesh; Pallas kernels take over on real TPU."""

from .attention import flash_attention

__all__ = ["flash_attention"]
