"""TPU compute kernels: flash/ring/Ulysses attention, MoE dispatch.
XLA blockwise fallbacks keep every op runnable on the CPU test mesh;
Pallas kernels take over on real TPU."""

from .attention import flash_attention
from .moe import MoEConfig, init_moe_params, moe_ffn, top_k_gating
from .ring_attention import ring_attention, ring_attention_sharded
from .ulysses import ulysses_attention

__all__ = [
    "MoEConfig",
    "flash_attention",
    "init_moe_params",
    "moe_ffn",
    "ring_attention",
    "ring_attention_sharded",
    "top_k_gating",
    "ulysses_attention",
]
