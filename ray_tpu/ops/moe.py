"""Mixture-of-Experts: top-k routing + expert-parallel dispatch.

Absent from the reference (SURVEY.md §2.5 — MoE delegated to
vLLM/deepspeed downstream); built TPU-native. The dispatch/combine are
dense einsums against a capacity-bounded one-hot dispatch tensor — the
MXU-friendly formulation (no gathers/scatters, static shapes), with the
expert dimension sharded over the `expert` mesh axis so XLA lowers the
dispatch einsum into an all-to-all over ICI.

Pieces:
- ``top_k_gating``: softmax router with top-k, capacity dropping, and
  the standard load-balancing auxiliary loss.
- ``moe_ffn``: routed expert FFN (SwiGLU experts) usable inside any
  jitted model; shard params' leading E dim on the `expert` axis.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class GatingResult(NamedTuple):
    dispatch: jax.Array  # (T, E, C) one-hot-ish dispatch weights in {0,1}
    combine: jax.Array  # (T, E, C) combine weights (gate probs)
    aux_loss: jax.Array  # scalar load-balance loss
    expert_load: jax.Array  # (E,) fraction of tokens per expert


def top_k_gating(
    logits: jax.Array,  # (T, E) router logits
    *,
    k: int = 2,
    capacity_factor: float = 1.25,
    min_capacity: int = 4,
    token_mask: jax.Array = None,  # (T,) 1=real token, 0=padding
) -> GatingResult:
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    capacity = max(min_capacity, int(math.ceil(T * k * capacity_factor / E)))

    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # (T, k)
    # renormalize the selected gates
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    # position of each (token, choice) within its expert's capacity:
    # cumulative count of prior assignments to the same expert
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # (T, k, E)
    if token_mask is not None:
        # padding tokens get no expert: they consume no capacity, emit
        # zero output, and are excluded from the balance statistics —
        # otherwise the router learns to balance pad tokens
        m32 = token_mask.astype(jnp.float32).reshape(T)
        gate_vals = gate_vals * m32[:, None]
        onehot = onehot * token_mask.astype(jnp.int32).reshape(T, 1, 1)
    flat = onehot.reshape(T * k, E)
    # priority order: all k=0 choices first, then k=1 (standard
    # switch/gshard ordering keeps top-1 assignments dense)
    order = jnp.arange(T * k).reshape(T, k).T.reshape(-1)  # choice-major
    flat_ordered = flat[order]
    pos_ordered = jnp.cumsum(flat_ordered, axis=0) - flat_ordered  # (T*k, E)
    inv = jnp.argsort(order)
    pos = pos_ordered[inv].reshape(T, k, E)
    slot = (pos * onehot).sum(-1)  # (T, k) slot within expert
    keep = slot < capacity

    keep_f = keep[:, :, None, None].astype(jnp.float32)
    if token_mask is not None:
        keep_f = keep_f * token_mask.astype(jnp.float32).reshape(T, 1, 1, 1)
    disp = (
        jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)[..., None]
        * jax.nn.one_hot(slot, capacity, dtype=jnp.float32)[:, :, None, :]
        * keep_f
    )  # (T, k, E, C)
    dispatch = disp.sum(1)  # (T, E, C)
    combine = (disp * gate_vals[:, :, None, None]).sum(1)

    # load-balance aux loss (Switch Transformer): E * sum(f_e * p_e),
    # statistics over REAL tokens only when a mask is given
    if token_mask is not None:
        m32 = token_mask.astype(jnp.float32).reshape(T)
        denom = jnp.maximum(m32.sum(), 1.0)
        me = (probs * m32[:, None]).sum(0) / denom
        ce = onehot.sum(1).astype(jnp.float32).sum(0) / denom
    else:
        me = probs.mean(0)  # mean router prob per expert
        ce = onehot.sum(1).astype(jnp.float32).mean(0)  # fraction routed (pre-drop)
    aux = (me * ce).sum() * E
    return GatingResult(dispatch, combine, aux, ce)


@dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    n_experts: int
    k: int = 2
    capacity_factor: float = 1.25


def init_moe_params(key, config: MoEConfig, dtype=jnp.bfloat16):
    kw, k1, k2, k3 = jax.random.split(key, 4)
    E, D, F = config.n_experts, config.d_model, config.d_ff
    s_in = 1.0 / math.sqrt(D)
    s_out = 1.0 / math.sqrt(F)
    return {
        "router": (jax.random.normal(kw, (D, E)) * s_in).astype(jnp.float32),
        "w_gate": (jax.random.normal(k1, (E, D, F)) * s_in).astype(dtype),
        "w_up": (jax.random.normal(k2, (E, D, F)) * s_in).astype(dtype),
        "w_down": (jax.random.normal(k3, (E, F, D)) * s_out).astype(dtype),
    }


def moe_ffn(
    params: dict,
    x: jax.Array,  # (B, S, D)
    config: MoEConfig,
    mask: jax.Array = None,  # (B, S) 1=real token, 0=padding
) -> Tuple[jax.Array, jax.Array]:
    """Routed SwiGLU expert FFN. Returns (out (B,S,D), aux_loss).

    Shard ``params['w_*']`` dim 0 on the `expert` mesh axis and the
    dispatched tokens follow via GSPMD all-to-all; activations stay
    sharded over batch/sequence axes.
    """
    B, S, D = x.shape
    T = B * S
    xt = x.reshape(T, D)
    logits = xt.astype(jnp.float32) @ params["router"]
    gate = top_k_gating(
        logits,
        k=config.k,
        capacity_factor=config.capacity_factor,
        token_mask=None if mask is None else mask.reshape(T),
    )
    # dispatch: (T,D),(T,E,C) -> (E,C,D)
    xe = jnp.einsum("td,tec->ecd", xt, gate.dispatch.astype(x.dtype))
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, params["w_gate"])) * jnp.einsum(
        "ecd,edf->ecf", xe, params["w_up"]
    )
    ye = jnp.einsum("ecf,efd->ecd", h, params["w_down"])  # (E,C,D)
    # combine back: (E,C,D),(T,E,C) -> (T,D)
    out = jnp.einsum("ecd,tec->td", ye, gate.combine.astype(x.dtype))
    return out.reshape(B, S, D), gate.aux_loss
