"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all reshard.

Absent from the reference (SURVEY.md §5.7); built TPU-native. Where
ring attention rotates K/V around the ring, Ulysses does two
all-to-alls: reshard activations from sequence-sharded to HEAD-sharded
(each chip gets the FULL sequence for a subset of heads), run ordinary
local attention, then reshard back. On TPU the all-to-all is a single
XLA collective over ICI; it's preferable to the ring when
heads >= seq-parallel degree and the sequence fits per-chip HBM at
S × H/N.

Call inside shard_map with the sequence axis bound to ``axis_name``:

    out = ulysses_attention(q, k, v, axis_name="seq")

q (B, S_local, H, hd); requires H % axis_size == 0 and
KVH % axis_size == 0 (pad KV heads up to the degree for stronger GQA).
"""

from __future__ import annotations

import jax

from .attention import flash_attention


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """(B, S/N, H, hd) seq-sharded -> (B, S, H/N, hd) head-sharded."""
    # all_to_all: split the head dim across the axis, gather sequence
    return jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """(B, S, H/N, hd) head-sharded -> (B, S/N, H, hd) seq-sharded."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = True,
) -> jax.Array:
    n = jax.lax.psum(1, axis_name)
    B, S_local, H, hd = q.shape
    KVH = k.shape[2]
    if H % n != 0:
        raise ValueError(f"n_heads {H} must divide by seq-parallel degree {n}")
    if KVH % n != 0:
        raise ValueError(
            f"n_kv_heads {KVH} must divide by seq-parallel degree {n}; "
            "replicate/pad KV heads up to the degree for GQA models"
        )
    qh = _seq_to_heads(q, axis_name)  # (B, S, H/N, hd)
    kh = _seq_to_heads(k, axis_name)
    vh = _seq_to_heads(v, axis_name)
    out = flash_attention(qh, kh, vh, causal=causal)
    return _heads_to_seq(out, axis_name)
