"""Ring attention: sequence/context parallelism over the `seq` mesh axis.

Absent from the reference (SURVEY.md §2.5, §5.7 — no ring-attention/
Ulysses/context-parallel code exists in its tree); built TPU-native:
Q/K/V are sharded over sequence on the `seq` axis; each of the N chips
computes blockwise attention of its local Q against the K/V shard it
currently holds, then rotates K/V one hop around the ICI ring with
`lax.ppermute`. After N steps every Q shard has attended to the full
sequence with O(S/N) memory per chip, and the permute of step i
overlaps the compute of step i+1 (XLA's latency-hiding scheduler
overlaps independent collective/compute on TPU).

Causality is exact across shards: each rotating K/V shard carries its
absolute offset into the blockwise mask, and fully-future shards
contribute exactly nothing (see _blockwise_accum's masked-probability
handling).

Usage: inside shard_map with an axis named ``axis_name``:

    out = ring_attention(q_shard, k_shard, v_shard, axis_name="seq")

or use ``ring_attention_sharded`` (this module) for the jit-level
wrapper that builds the shard_map against a mesh.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P

from .attention import (
    _blockwise_accum,
    finalize_attention_state,
    init_attention_state,
)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    block_q: int = 512,
    block_kv: int = 512,
) -> jax.Array:
    """Per-shard ring attention; call inside shard_map/pmap with
    ``axis_name`` bound. q (B, S_local, H, hd); k/v (B, S_local, KVH, hd).
    """
    B, Sq, H, hd = q.shape
    KVH = k.shape[2]
    G = H // KVH
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    q_off = idx * Sq
    qg = q.reshape(B, Sq, KVH, G, hd)

    acc, m, l = init_attention_state(B, Sq, KVH, G, hd)

    def step(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # shard currently held started at rank (idx - i) mod n
        kv_idx = jax.lax.rem(idx - i + n, n)
        kv_off = kv_idx * k_cur.shape[1]
        acc, m, l = _blockwise_accum(
            qg, k_cur, v_cur, acc, m, l,
            causal=causal, block_q=block_q, block_kv=block_kv,
            q_offset=q_off, kv_offset=kv_off,
        )
        # rotate KV one hop: rank r hands its shard to r+1 (ring on ICI)
        k_nxt = jax.lax.ppermute(
            k_cur, axis_name, [(r, (r + 1) % n) for r in range(n)]
        )
        v_nxt = jax.lax.ppermute(
            v_cur, axis_name, [(r, (r + 1) % n) for r in range(n)]
        )
        return acc, m, l, k_nxt, v_nxt

    # n (a mesh axis size) is a static Python int under shard_map —
    # psum of a constant folds — so a Python loop unrolls the ring,
    # keeping each step's permute/compute visible to XLA's scheduler
    # for compute/communication overlap.
    carry = (acc, m, l, k, v)
    for i in range(int(n)):
        carry = step(i, carry)
    acc, m, l, _, _ = carry
    out = finalize_attention_state(acc, l)
    return out.reshape(B, Sq, H, hd).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    block_q: int = 512,
    block_kv: int = 512,
) -> jax.Array:
    """Jit-level wrapper for a single-axis seq mesh: S sharded over
    ``axis_name``, B/H replicated. For multi-axis meshes (batch on
    data/fsdp, heads on model) build the shard_map directly with the
    full spec — see models/llama.py _attention_ring."""
    from jax import shard_map

    spec = P(None, axis_name, None, None)

    fn = functools.partial(
        ring_attention,
        axis_name=axis_name,
        causal=causal,
        block_q=block_q,
        block_kv=block_kv,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
