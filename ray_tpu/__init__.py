"""ray_tpu: a TPU-native distributed computing framework.

The programming model of Ray — tasks, actors, objects, placement
groups, and the ML libraries on top — re-designed for TPU hosts and
pods: scheduling understands chips/slices as gang resources, the
collective plane is XLA programs over an ICI mesh (not NCCL), and the
training stack is jit/pjit/shard_map-first.

Public surface parity tracked against the reference's python/ray/
__init__.py: init, shutdown, remote, get, put, wait, kill, cancel,
get_actor, ObjectRef, actor/task options, cluster introspection.
"""

from __future__ import annotations

from typing import Any

from . import exceptions
from ._private.worker import (
    available_resources,
    cancel,
    cluster_resources,
    free,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    shutdown,
    timeline,
    wait,
)
from .actor import ActorClass, ActorHandle
from .job_config import JobConfig
from .object_ref import ObjectRef, ObjectRefGenerator
from .remote_function import RemoteFunction
from .runtime_context import get_runtime_context

__version__ = "0.1.0"


def remote(*args, **kwargs):
    """Turn a function into a RemoteFunction or a class into an ActorClass.

    Usable bare (`@remote`) or with options (`@remote(num_tpus=1)`).
    Parity: ray.remote (python/ray/_private/worker.py:3407).
    """

    def wrap(target):
        if isinstance(target, type):
            return ActorClass(target, kwargs)
        if callable(target):
            return RemoteFunction(target, kwargs)
        raise TypeError("@remote requires a function or class")

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return wrap(args[0])
    if args:
        raise TypeError("@remote with arguments must use keyword options, e.g. @remote(num_cpus=2)")
    return wrap


def method(**kwargs):
    """Decorator for actor methods carrying default options (ray.method parity)."""

    def decorator(fn):
        fn.__ray_method_options__ = kwargs
        return fn

    return decorator


__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "free",
    "get_actor",
    "available_resources",
    "cluster_resources",
    "nodes",
    "JobConfig",
    "ObjectRef",
    "ObjectRefGenerator",
    "timeline",
    "ActorClass",
    "ActorHandle",
    "RemoteFunction",
    "get_runtime_context",
    "exceptions",
]
