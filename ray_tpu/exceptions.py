"""User-visible exceptions.

Parity with the reference's python/ray/exceptions.py: RayError,
RayTaskError, RayActorError/ActorDiedError, GetTimeoutError,
WorkerCrashedError, ObjectLostError, TaskCancelledError.
"""

from __future__ import annotations


class RayError(Exception):
    """Base class for all framework errors."""


class TaskError(RayError):
    """A task raised an exception during execution.

    Carries the remote traceback; re-raised at every `get()` on the
    task's return refs (reference behavior: python/ray/exceptions.py
    RayTaskError wraps the cause and as_instanceof_cause()).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (TaskError, (self.function_name, self.traceback_str, self.cause))


class WorkerCrashedError(RayError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead; pending and future calls fail with this."""

    def __init__(self, actor_id=None, msg: str = "The actor died."):
        self.actor_id = actor_id
        self.msg = msg
        super().__init__(msg)

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id, self.msg))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unavailable (e.g. restarting)."""


class GetTimeoutError(RayError, TimeoutError):
    """`get(timeout=...)` expired before the object became available."""


class ObjectLostError(RayError):
    """The object's value was lost and could not be reconstructed."""


class TaskCancelledError(RayError):
    """The task was cancelled before/while running."""


class RuntimeEnvSetupError(RayError):
    """Preparing the runtime environment for a task/actor failed."""


class OutOfMemoryError(RayError):
    """A worker was killed by the memory monitor."""


class TaskTimeoutError(RayError):
    """The task's execute exceeded options(timeout_s=...) (or the
    cluster-wide hung-worker watchdog deadline) and its retry budget is
    exhausted. The runtime SIGKILLs the stalled worker — a hung process
    (e.g. a SIGSTOP'd or deadlocked worker) never EOFs on its own — and
    retries the task first; this error is the give-up."""


class RequestShedError(RayError):
    """Serve admission control shed this request: the deployment's
    ``max_queued_requests`` cap was reached, so the router refused it
    immediately instead of queueing it into a timeout. Retriable — the
    HTTP ingress maps it to 503 with a Retry-After hint."""

    def __init__(self, deployment: str = "", queued: int = 0, cap: int = 0):
        self.deployment = deployment
        self.queued = queued
        self.cap = cap
        super().__init__(
            f"request to deployment {deployment!r} shed: "
            f"{queued} outstanding >= max_queued_requests={cap}"
        )

    def __reduce__(self):
        return (RequestShedError, (self.deployment, self.queued, self.cap))


class RequestExpiredError(RayError, TimeoutError):
    """The request's deadline passed before the user callable ran (in
    the router's replica wait, the replica's pre-execute check, or the
    batch queue). Dropped without burning replica time; the HTTP
    ingress maps it to 504."""

    def __init__(self, deployment: str = "", msg: str = ""):
        self.deployment = deployment
        self.msg = msg or (
            f"request to deployment {deployment!r} expired before execute"
        )
        super().__init__(self.msg)

    def __reduce__(self):
        return (RequestExpiredError, (self.deployment, self.msg))


# Reference-compatible aliases
RayTaskError = TaskError
RayActorError = ActorError
