"""Developer tooling that ships with the repo (not part of the runtime
API surface). Currently: :mod:`ray_tpu.tools.graftlint`."""
