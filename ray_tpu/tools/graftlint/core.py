"""graftlint core: findings, suppression, baseline, and the file runner.

graftlint is a repo-specific static analyzer for the concurrency and
distributed-runtime invariants of this codebase (see README.md in this
directory). It is stdlib-only (`ast` + `json`) so it can run inside the
tier-1 test gate with no extra dependencies.

Design notes:

- Checkers are plain functions ``check(ctx) -> list[Finding]`` registered
  via :func:`register`. Keeping them stateless functions (no accumulating
  instance attributes) is deliberate — the analyzer lints its own package.
- Whole-program passes (GL012+) are ``check(session) -> list[Finding]``
  functions registered via :func:`register_project`; ``check_paths``
  builds one :class:`~.project.ProjectSession` over the full file list
  and runs them after the per-file rules.
- Every file is parsed exactly ONCE per process, whatever the number of
  checkers or passes that look at it: :func:`parse_cached` keys on
  ``(mtime_ns, size)`` so per-file rules, the project session, and
  repeated test invocations all share one AST.
- Findings are fingerprinted as ``(path, code, symbol)`` rather than by
  line number, so a baseline survives unrelated edits to the same file.
- Two suppression mechanisms:
  * inline: a ``# graftlint: disable=GL001,GL004`` (or bare
    ``# graftlint: disable``) comment on the flagged line;
  * baseline: a JSON file of fingerprints for accepted findings, loaded
    with ``--baseline`` (the packaged ``baseline.json`` by default).
"""

from __future__ import annotations

import ast
import json
import os
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "FileContext",
    "register",
    "register_project",
    "all_checkers",
    "all_project_checkers",
    "check_file",
    "check_paths",
    "parse_cached",
    "parse_stats",
    "load_baseline",
    "write_baseline",
    "DEFAULT_BASELINE_PATH",
]

DEFAULT_BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

_DISABLE_MARKER = "graftlint: disable"


@dataclass(frozen=True)
class Finding:
    """One reported violation.

    ``symbol`` is a stable anchor (usually ``Class.method`` or
    ``Class.method.attr``) used for baseline fingerprints instead of the
    line number, which churns with unrelated edits.
    """

    path: str
    line: int
    code: str
    message: str
    symbol: str = ""

    def fingerprint(self) -> Tuple[str, str, str]:
        return (_norm_path(self.path), self.code, self.symbol)

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.code} {self.message}"


@dataclass
class FileContext:
    """Everything a checker gets to look at for one file."""

    path: str
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    # module alias -> full module name ("np" -> "numpy"); from-imports
    # map the bound name to its dotted origin ("sleep" -> "time.sleep")
    import_aliases: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, source: Optional[str] = None) -> "FileContext":
        if source is None:
            with tokenize.open(path) as f:
                source = f.read()
        parse_stats["parses"] += 1
        tree = ast.parse(source, filename=path)
        ctx = cls(path=path, source=source, tree=tree,
                  lines=source.splitlines())
        ctx.import_aliases = _collect_imports(tree)
        return ctx

    def resolve(self, dotted: Optional[str]) -> Optional[str]:
        """Expand the leading segment of a dotted name through the
        file's imports: with ``import numpy as np``, ``np.ones`` ->
        ``numpy.ones``; with ``from time import sleep``, ``sleep`` ->
        ``time.sleep``."""
        if dotted is None:
            return None
        head, sep, rest = dotted.partition(".")
        full = self.import_aliases.get(head)
        if full is None:
            return dotted
        return full + sep + rest


# --------------------------------------------------------------- parse cache
#
# One process-wide AST cache: 11 per-file rules plus the six
# whole-program passes all want the same tree, and the tier-1 gate
# re-lints the full
# package several times per test run (fixtures, revert tests, the gate
# itself). Keyed on (mtime_ns, size) so an edited fixture file re-parses
# while untouched runtime files never do. ``parse_stats`` is exported so
# tests can assert the single-parse property directly.

_PARSE_CACHE: Dict[str, Tuple[Tuple[int, int], "FileContext"]] = {}
parse_stats = {"parses": 0, "hits": 0}


def parse_cached(path: str) -> "FileContext":
    """FileContext for ``path``, parsed at most once per file version."""
    try:
        st = os.stat(path)
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        key = None
    if key is not None:
        hit = _PARSE_CACHE.get(path)
        if hit is not None and hit[0] == key:
            parse_stats["hits"] += 1
            return hit[1]
    ctx = FileContext.parse(path)
    if key is not None:
        _PARSE_CACHE[path] = (key, ctx)
    return ctx


# ------------------------------------------------------------------ registry

CheckerFn = Callable[[FileContext], List[Finding]]
_CHECKERS: List[Tuple[str, str, CheckerFn]] = []
# whole-program passes: fn(session: project.ProjectSession) -> findings
_PROJECT_CHECKERS: List[Tuple[str, str, Callable]] = []


def register(code: str, name: str) -> Callable[[CheckerFn], CheckerFn]:
    def deco(fn: CheckerFn) -> CheckerFn:
        _CHECKERS.append((code, name, fn))
        return fn

    return deco


def register_project(code: str, name: str) -> Callable:
    def deco(fn):
        _PROJECT_CHECKERS.append((code, name, fn))
        return fn

    return deco


def all_checkers() -> List[Tuple[str, str, CheckerFn]]:
    from . import checkers as _checkers  # noqa: F401  (registration side effect)

    return list(_CHECKERS)


def all_project_checkers() -> List[Tuple[str, str, Callable]]:
    from . import checkers as _checkers  # noqa: F401  (registration side effect)

    return list(_PROJECT_CHECKERS)


# ------------------------------------------------------------------- helpers


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node: ast.AST) -> Optional[str]:
    """``x`` for ``self.x``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def walk_local(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class defs
    (so per-function analyses stay per-function)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(n))


def qualname_map(tree: ast.Module) -> Dict[int, str]:
    """``id(def-node) -> "Outer.inner"`` for every function/class def,
    so checkers can emit collision-free baseline symbols (two
    same-named methods in different classes must not share a
    fingerprint)."""
    out: Dict[int, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                out[id(child)] = qual
                visit(child, qual)
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _collect_imports(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _norm_path(path: str) -> str:
    """Stable fingerprint path: keep the trailing components from the
    package root down, so the baseline works from any CWD."""
    p = path.replace(os.sep, "/")
    for anchor in ("ray_tpu/", "tests/"):
        idx = p.find(anchor)
        if idx >= 0:
            return p[idx:]
    return os.path.basename(p)


# --------------------------------------------------------------- suppression


def _suppressed(finding: Finding, ctx: FileContext) -> bool:
    if 1 <= finding.line <= len(ctx.lines):
        line = ctx.lines[finding.line - 1]
        idx = line.find(_DISABLE_MARKER)
        if idx >= 0:
            spec = line[idx + len(_DISABLE_MARKER):].lstrip()
            if not spec.startswith("="):
                return True  # bare "graftlint: disable" — all codes
            codes = spec[1:].split("#", 1)[0]
            # tolerate trailing prose: "disable=GL004 — readiness poll"
            parts = {
                c.strip().split()[0]
                for c in codes.split(",")
                if c.strip()
            }
            return finding.code in parts
    return False


# ------------------------------------------------------------------ baseline


def load_baseline(path: Optional[str]) -> Set[Tuple[str, str, str]]:
    if path is None or not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return {
        (e["path"], e["code"], e.get("symbol", ""))
        for e in data.get("entries", [])
    }


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = sorted(
        {f.fingerprint() for f in findings},
    )
    data = {
        "version": 1,
        "comment": (
            "Accepted graftlint findings. Each entry is fingerprinted by "
            "(path, code, symbol), not line, so it survives unrelated "
            "edits. Remove entries as the underlying code is fixed."
        ),
        "entries": [
            {"path": p, "code": c, "symbol": s} for p, c, s in entries
        ],
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


# -------------------------------------------------------------------- runner


def check_file(
    path: str,
    source: Optional[str] = None,
    codes: Optional[Set[str]] = None,
) -> List[Finding]:
    """All (non-inline-suppressed) findings for one file.

    The whole-program passes run too, over a single-file session — so
    fixtures exercise GL012+ without a tree. Passes needing more than
    one module (GL012 is inert without a ``protocol`` module in the
    session) are exercised through ``check_paths``, whose ``overrides``
    let revert tests lint a modified copy of one real file against the
    rest of the live tree.
    """
    ctx, err = _parse_context(path, source)
    if ctx is None:
        return [err]
    out = _per_file_findings(ctx, codes)
    out.extend(_project_findings_for([ctx], codes))
    out.sort(key=lambda f: (f.path, f.line, f.code))
    return out


def _parse_context(
    path: str, source: Optional[str] = None
) -> Tuple[Optional[FileContext], Optional[Finding]]:
    """(context, None), or (None, GL000 finding) on a parse failure."""
    try:
        if source is None:
            return parse_cached(path), None
        return FileContext.parse(path, source), None
    except (SyntaxError, UnicodeDecodeError) as err:
        return None, Finding(
            path=path,
            line=getattr(err, "lineno", 1) or 1,
            code="GL000",
            message=f"could not parse: {err.__class__.__name__}: {err}",
            symbol="<parse>",
        )


def _per_file_findings(
    ctx: FileContext, codes: Optional[Set[str]]
) -> List[Finding]:
    """All non-suppressed per-file-rule findings for one context."""
    out: List[Finding] = []
    for code, _name, fn in all_checkers():
        if codes is not None and code not in codes:
            continue
        for f in fn(ctx):
            if not _suppressed(f, ctx):
                out.append(f)
    return out


def _project_findings_for(
    contexts: Sequence[FileContext], codes: Optional[Set[str]]
) -> List[Finding]:
    """Run the whole-program passes over one prepared session."""
    selected = [
        (code, name, fn)
        for code, name, fn in all_project_checkers()
        if codes is None or code in codes
    ]
    if not selected:
        return []
    from .project import ProjectSession

    session = ProjectSession(list(contexts))
    by_path = {ctx.path: ctx for ctx in contexts}
    out: List[Finding] = []
    for _code, _name, fn in selected:
        for f in fn(session):
            ctx = by_path.get(f.path)
            if ctx is None or not _suppressed(f, ctx):
                out.append(f)
    return out


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", "node_modules")
                )
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        yield os.path.join(root, fname)
        else:
            # a file named explicitly is linted regardless of extension
            # (e.g. an executable script) — silently skipping it would
            # report a false "0 finding(s)" green
            yield p


def check_paths(
    paths: Sequence[str],
    baseline: Optional[Set[Tuple[str, str, str]]] = None,
    codes: Optional[Set[str]] = None,
    overrides: Optional[Dict[str, str]] = None,
    report_only: Optional[Set[str]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Returns (new_findings, baselined_findings).

    The whole tree is parsed ONCE (per-file rules and the project
    session share the cache) and the whole-program passes run over one
    session covering every file.

    ``overrides`` maps path -> replacement source (revert tests lint a
    modified copy of a real file against the rest of the live tree).
    ``report_only`` restricts reported PER-FILE findings to those paths
    while still analyzing everything — the ``--changed-only`` mode.
    Whole-program findings always report: their anchor line can sit in
    an unchanged file while the causal edit is on the other side of the
    relationship (delete a handler and the sent-but-unhandled finding
    anchors at the untouched send site), so scoping them to the diff
    would green-light exactly the breakage the passes exist to catch.
    """
    baseline = baseline or set()
    overrides = overrides or {}
    report_abs = (
        None if report_only is None
        else {os.path.abspath(p) for p in report_only}
    )
    new: List[Finding] = []
    old: List[Finding] = []
    contexts: List[FileContext] = []
    per_file: List[Finding] = []
    for fpath in iter_python_files(paths):
        ctx, err = _parse_context(fpath, overrides.get(fpath))
        if ctx is None:
            per_file.append(err)
            continue
        contexts.append(ctx)
        per_file.extend(_per_file_findings(ctx, codes))
    if report_abs is not None:
        per_file = [
            f for f in per_file
            if os.path.abspath(f.path) in report_abs
        ]
    findings = per_file + _project_findings_for(contexts, codes)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    for f in findings:
        (old if f.fingerprint() in baseline else new).append(f)
    return new, old
