"""graftlint core: findings, suppression, baseline, and the file runner.

graftlint is a repo-specific static analyzer for the concurrency and
distributed-runtime invariants of this codebase (see README.md in this
directory). It is stdlib-only (`ast` + `json`) so it can run inside the
tier-1 test gate with no extra dependencies.

Design notes:

- Checkers are plain functions ``check(ctx) -> list[Finding]`` registered
  via :func:`register`. Keeping them stateless functions (no accumulating
  instance attributes) is deliberate — the analyzer lints its own package.
- Findings are fingerprinted as ``(path, code, symbol)`` rather than by
  line number, so a baseline survives unrelated edits to the same file.
- Two suppression mechanisms:
  * inline: a ``# graftlint: disable=GL001,GL004`` (or bare
    ``# graftlint: disable``) comment on the flagged line;
  * baseline: a JSON file of fingerprints for accepted findings, loaded
    with ``--baseline`` (the packaged ``baseline.json`` by default).
"""

from __future__ import annotations

import ast
import json
import os
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "FileContext",
    "register",
    "all_checkers",
    "check_file",
    "check_paths",
    "load_baseline",
    "write_baseline",
    "DEFAULT_BASELINE_PATH",
]

DEFAULT_BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

_DISABLE_MARKER = "graftlint: disable"


@dataclass(frozen=True)
class Finding:
    """One reported violation.

    ``symbol`` is a stable anchor (usually ``Class.method`` or
    ``Class.method.attr``) used for baseline fingerprints instead of the
    line number, which churns with unrelated edits.
    """

    path: str
    line: int
    code: str
    message: str
    symbol: str = ""

    def fingerprint(self) -> Tuple[str, str, str]:
        return (_norm_path(self.path), self.code, self.symbol)

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.code} {self.message}"


@dataclass
class FileContext:
    """Everything a checker gets to look at for one file."""

    path: str
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    # module alias -> full module name ("np" -> "numpy"); from-imports
    # map the bound name to its dotted origin ("sleep" -> "time.sleep")
    import_aliases: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, source: Optional[str] = None) -> "FileContext":
        if source is None:
            with tokenize.open(path) as f:
                source = f.read()
        tree = ast.parse(source, filename=path)
        ctx = cls(path=path, source=source, tree=tree,
                  lines=source.splitlines())
        ctx.import_aliases = _collect_imports(tree)
        return ctx

    def resolve(self, dotted: Optional[str]) -> Optional[str]:
        """Expand the leading segment of a dotted name through the
        file's imports: with ``import numpy as np``, ``np.ones`` ->
        ``numpy.ones``; with ``from time import sleep``, ``sleep`` ->
        ``time.sleep``."""
        if dotted is None:
            return None
        head, sep, rest = dotted.partition(".")
        full = self.import_aliases.get(head)
        if full is None:
            return dotted
        return full + sep + rest


# ------------------------------------------------------------------ registry

CheckerFn = Callable[[FileContext], List[Finding]]
_CHECKERS: List[Tuple[str, str, CheckerFn]] = []


def register(code: str, name: str) -> Callable[[CheckerFn], CheckerFn]:
    def deco(fn: CheckerFn) -> CheckerFn:
        _CHECKERS.append((code, name, fn))
        return fn

    return deco


def all_checkers() -> List[Tuple[str, str, CheckerFn]]:
    from . import checkers as _checkers  # noqa: F401  (registration side effect)

    return list(_CHECKERS)


# ------------------------------------------------------------------- helpers


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node: ast.AST) -> Optional[str]:
    """``x`` for ``self.x``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def walk_local(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class defs
    (so per-function analyses stay per-function)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(n))


def qualname_map(tree: ast.Module) -> Dict[int, str]:
    """``id(def-node) -> "Outer.inner"`` for every function/class def,
    so checkers can emit collision-free baseline symbols (two
    same-named methods in different classes must not share a
    fingerprint)."""
    out: Dict[int, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                out[id(child)] = qual
                visit(child, qual)
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _collect_imports(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _norm_path(path: str) -> str:
    """Stable fingerprint path: keep the trailing components from the
    package root down, so the baseline works from any CWD."""
    p = path.replace(os.sep, "/")
    for anchor in ("ray_tpu/", "tests/"):
        idx = p.find(anchor)
        if idx >= 0:
            return p[idx:]
    return os.path.basename(p)


# --------------------------------------------------------------- suppression


def _suppressed(finding: Finding, ctx: FileContext) -> bool:
    if 1 <= finding.line <= len(ctx.lines):
        line = ctx.lines[finding.line - 1]
        idx = line.find(_DISABLE_MARKER)
        if idx >= 0:
            spec = line[idx + len(_DISABLE_MARKER):].lstrip()
            if not spec.startswith("="):
                return True  # bare "graftlint: disable" — all codes
            codes = spec[1:].split("#", 1)[0]
            # tolerate trailing prose: "disable=GL004 — readiness poll"
            parts = {
                c.strip().split()[0]
                for c in codes.split(",")
                if c.strip()
            }
            return finding.code in parts
    return False


# ------------------------------------------------------------------ baseline


def load_baseline(path: Optional[str]) -> Set[Tuple[str, str, str]]:
    if path is None or not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return {
        (e["path"], e["code"], e.get("symbol", ""))
        for e in data.get("entries", [])
    }


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = sorted(
        {f.fingerprint() for f in findings},
    )
    data = {
        "version": 1,
        "comment": (
            "Accepted graftlint findings. Each entry is fingerprinted by "
            "(path, code, symbol), not line, so it survives unrelated "
            "edits. Remove entries as the underlying code is fixed."
        ),
        "entries": [
            {"path": p, "code": c, "symbol": s} for p, c, s in entries
        ],
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


# -------------------------------------------------------------------- runner


def check_file(
    path: str,
    source: Optional[str] = None,
    codes: Optional[Set[str]] = None,
) -> List[Finding]:
    """All (non-inline-suppressed) findings for one file."""
    try:
        ctx = FileContext.parse(path, source)
    except (SyntaxError, UnicodeDecodeError) as err:
        return [
            Finding(
                path=path,
                line=getattr(err, "lineno", 1) or 1,
                code="GL000",
                message=f"could not parse: {err.__class__.__name__}: {err}",
                symbol="<parse>",
            )
        ]
    out: List[Finding] = []
    for code, _name, fn in all_checkers():
        if codes is not None and code not in codes:
            continue
        for f in fn(ctx):
            if not _suppressed(f, ctx):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.code))
    return out


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", "node_modules")
                )
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        yield os.path.join(root, fname)
        else:
            # a file named explicitly is linted regardless of extension
            # (e.g. an executable script) — silently skipping it would
            # report a false "0 finding(s)" green
            yield p


def check_paths(
    paths: Sequence[str],
    baseline: Optional[Set[Tuple[str, str, str]]] = None,
    codes: Optional[Set[str]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Returns (new_findings, baselined_findings)."""
    baseline = baseline or set()
    new: List[Finding] = []
    old: List[Finding] = []
    for fpath in iter_python_files(paths):
        for f in check_file(fpath, codes=codes):
            (old if f.fingerprint() in baseline else new).append(f)
    return new, old
