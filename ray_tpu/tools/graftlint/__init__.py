"""graftlint: AST-based concurrency & distributed-runtime invariant
checker for this repository. See README.md in this directory for the
rule catalogue and ``python -m ray_tpu.tools.graftlint --help`` for the
CLI."""

from .core import (  # noqa: F401
    DEFAULT_BASELINE_PATH,
    Finding,
    all_checkers,
    all_project_checkers,
    check_file,
    check_paths,
    load_baseline,
    write_baseline,
)

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "Finding",
    "all_checkers",
    "all_project_checkers",
    "check_file",
    "check_paths",
    "load_baseline",
    "write_baseline",
]
