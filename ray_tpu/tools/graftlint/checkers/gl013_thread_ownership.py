"""GL013 — thread-ownership conformance (the whole-program GL010).

GL010 polices one hand-labelled boundary (reactor classes must not
touch hub/service state) by *syntactic base names*. This pass infers
the ownership map instead: every thread entry point the repo actually
has — ``threading.Thread(target=...)`` constructions, Thread-subclass /
reactor ``run`` methods, ``CoreClient._read_loop``, dispatch-table
handlers (they run wherever their dispatcher runs), ``_add_timer``
callbacks — seeds a **domain**, and domains propagate through the
intra-class call graph. Code whose domain we cannot see (public API
methods called from arbitrary user threads) is NOT policed: the pass
reports only conflicts between two *known* domains, which keeps it
quiet on the tree and loud on the bug class it exists for.

Findings:

1. *intra-class conflict* — an attribute written in one domain and
   read/written in another, with no lock held at either site. Exempt:
   ``__init__`` writes (construction happens-before thread start),
   channel attributes (rings/queues/events/locks — mutating one IS the
   sanctioned crossing), and GIL-atomic flag attributes whose every
   write stores a constant (``self._running = False`` — the repo's
   cooperative-shutdown idiom);
2. *cross-object call* — a domain-owned method calling a method that
   is owned by a DIFFERENT domain of another class, e.g. the
   first-draft bug this rule re-catches: a reactor shard calling
   ``hub._handle_disconnect(conn)`` directly instead of pushing
   ``CONN_LOST`` onto its state ring. The ring crossing
   (``self._state_ring.push(...)``) passes because ``ShardRing`` has
   no thread domains — its whole point is to be safely shared;
3. *cross-object write* — a domain-owned method writing attributes of
   an instance whose class runs under a disjoint domain set.
   Construction is exempt: a function that just built the object (and
   hasn't started its thread) owns it outright;
4. *cross-object read* of an attribute the owning class writes
   post-init from its own domains (reading a foreign thread's mutable
   state without a lock). Reads of construction-set attributes and of
   stats objects without domains stay legal — scrape-time reads of
   monotonic counters are a documented pattern here.

Type inference is deliberately modest (constructor assignments,
annotations, iteration over known collections, and a name fallback
``self.hub`` -> class ``Hub``); what it cannot resolve it does not
flag.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, register_project, self_attr, walk_local
from ..project import (
    ClassThreads,
    ProjectSession,
    ThreadModel,
    is_lockish as _is_lockish,
)

_CODE = "GL013"
_MUTATORS = {
    "append", "extend", "insert", "add", "pop", "popleft", "popitem",
    "remove", "discard", "clear", "update", "setdefault", "appendleft",
    "move_to_end", "put", "put_nowait",
}


def _lock_with(node: ast.AST) -> bool:
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        attr = self_attr(item.context_expr)
        if attr is not None and _is_lockish(attr):
            return True
        if isinstance(item.context_expr, ast.Name) and _is_lockish(
                item.context_expr.id):
            return True
    return False


def _locked_ids(fn: ast.AST) -> Set[int]:
    out: Set[int] = set()
    for n in ast.walk(fn):
        if _lock_with(n):
            for sub in ast.walk(n):
                out.add(id(sub))
    return out


def _const_flag_attrs(info: ClassThreads) -> Set[str]:
    """Attributes whose every write (anywhere in the class) assigns a
    bare constant — GIL-atomic signal flags like ``self._running``."""
    methods = info.module.methods(info.cls)
    flag: Dict[str, bool] = {}
    for fn in methods.values():
        for n in ast.walk(fn):
            if isinstance(n, (ast.Assign, ast.AnnAssign)):
                if isinstance(n, ast.AnnAssign) and n.value is None:
                    continue  # bare annotation: declares, assigns nothing
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    a = self_attr(t)
                    if a is None:
                        continue
                    is_const = isinstance(n.value, ast.Constant)
                    flag[a] = flag.get(a, True) and is_const
            elif isinstance(n, ast.AugAssign):
                a = self_attr(n.target)
                if a is not None:
                    flag[a] = False
    return {a for a, ok in flag.items() if ok}


def _attr_accesses(
    fn: ast.AST,
) -> List[Tuple[str, str, int, bool]]:
    """(attr, kind, line, locked) for self.<attr> accesses in fn:
    kind is "read" or "write" (assign/augassign/subscript store/
    mutator call/delete)."""
    locked = _locked_ids(fn)
    out: List[Tuple[str, str, int, bool]] = []
    for n in walk_local(fn):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if isinstance(n, ast.AnnAssign) and n.value is None:
                continue  # bare annotation: declares, assigns nothing
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                a = self_attr(t)
                if a is not None:
                    out.append((a, "write", n.lineno, id(n) in locked))
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    a = self_attr(t.value)
                    if a is not None:
                        out.append((a, "write", n.lineno, id(n) in locked))
        elif (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in _MUTATORS
        ):
            a = self_attr(n.func.value)
            if a is not None:
                out.append((a, "write", n.lineno, id(n) in locked))
        elif isinstance(n, ast.Delete):
            for t in n.targets:
                if isinstance(t, ast.Subscript):
                    a = self_attr(t.value)
                    if a is not None:
                        out.append((a, "write", n.lineno, id(n) in locked))
        elif isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            a = self_attr(n)
            if a is not None:
                out.append((a, "read", n.lineno, id(n) in locked))
    return out


def _intra_class(info: ClassThreads) -> List[Finding]:
    if len(info.all_domains()) < 2:
        return []
    methods = info.module.methods(info.cls)
    flags = _const_flag_attrs(info)
    # attr -> [(kind, domains, method, line)]
    acc: Dict[str, List[Tuple[str, Set[str], str, int]]] = {}
    for mname, fn in methods.items():
        domains = info.domains.get(mname) or set()
        if not domains or mname == "__init__":
            continue
        for attr, kind, line, locked in _attr_accesses(fn):
            if locked or _is_lockish(attr) or attr in info.channel_attrs:
                continue
            if attr in flags:
                continue
            acc.setdefault(attr, []).append((kind, domains, mname, line))
    out: List[Finding] = []
    for attr, uses in sorted(acc.items()):
        writes = [u for u in uses if u[0] == "write"]
        for _k, wdoms, wmeth, wline in writes:
            clash = next(
                (
                    u for u in uses
                    if not (wdoms & u[1])
                ),
                None,
            )
            if clash is None:
                continue
            _ck, cdoms, cmeth, _cline = clash
            out.append(Finding(
                path=info.module.path,
                line=wline,
                code=_CODE,
                message=(
                    f"`self.{attr}` is written in {info.cls.name}."
                    f"{wmeth} under {_fmt(wdoms)} and accessed in "
                    f"{cmeth} under {_fmt(cdoms)} with no lock at "
                    f"either site — cross-thread state needs a lock, a "
                    f"ring/queue crossing, or single-domain ownership"
                ),
                symbol=f"{info.cls.name}.{wmeth}.{attr}",
            ))
            break  # one finding per written attr
    return out


def _fmt(domains: Set[str]) -> str:
    return "{" + ", ".join(sorted(domains)) + "}"


# ----------------------------------------------------------- cross-object


def _name_fallback(session: ProjectSession, name: str) -> Optional[str]:
    """``self.hub`` -> class Hub when the tree defines exactly such a
    class (case-insensitive exact match on the bare name)."""
    for cls_name in session.class_index:
        if cls_name.lower() == name.lower():
            return cls_name
    return None


def _local_types(
    session: ProjectSession, info: ClassThreads, fn: ast.FunctionDef,
) -> Tuple[Dict[str, str], Set[str]]:
    """(local/attr base -> class name, construction-phase bases).

    Bases constructed *in this function* (``shards = [ReactorShard(...)
    ...]``) are construction-phase: the builder owns the object until
    its thread starts, so accesses here are exempt."""
    from ..project import _annotation_class, _ctor_class  # reuse inference

    types: Dict[str, str] = {}
    constructed: Set[str] = set()
    for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
        ann = _annotation_class(arg.annotation)
        if ann and session.class_index.get(ann):
            types[arg.arg] = ann
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            ctor = _ctor_class(node.value)
            src_attr = None
            v = node.value
            while isinstance(v, ast.Subscript):
                v = v.value
            src_attr = self_attr(v)
            for t in node.targets:
                names = []
                if isinstance(t, ast.Name):
                    names = [t.id]
                a = self_attr(t)
                if a is not None:
                    names.append(f"self.{a}")
                for nm in names:
                    if ctor and session.class_index.get(ctor):
                        types[nm] = ctor
                        constructed.add(nm)
                    elif src_attr and src_attr in info.attr_types:
                        types[nm] = info.attr_types[src_attr]
                    elif isinstance(node.value, ast.Name) and \
                            node.value.id in types:
                        types[nm] = types[node.value.id]
        elif isinstance(node, ast.For):
            a = self_attr(node.iter)
            elem = None
            if a is not None and a in info.attr_types:
                elem = info.attr_types[a]
            elif isinstance(node.iter, ast.Name) and node.iter.id in types:
                elem = types[node.iter.id]
            if elem and isinstance(node.target, ast.Name):
                types[node.target.id] = elem
                if node.iter and isinstance(node.iter, ast.Name) and \
                        node.iter.id in constructed:
                    constructed.add(node.target.id)
    for a, t in info.attr_types.items():
        types.setdefault(f"self.{a}", t)
    return types, constructed


def _base_key(node: ast.AST) -> Optional[str]:
    """Lookup key for the base of an attribute access: ``self.hub`` ->
    "self.hub", ``s`` -> "s", ``self.shards[i]`` -> "self.shards"."""
    while isinstance(node, ast.Subscript):
        node = node.value
    a = self_attr(node)
    if a is not None:
        return f"self.{a}"
    if isinstance(node, ast.Name) and node.id != "self":
        return node.id
    return None


def _domain_written_attrs(info: ClassThreads) -> Dict[str, Set[str]]:
    """attr -> domains of methods that write it post-init."""
    methods = info.module.methods(info.cls)
    out: Dict[str, Set[str]] = {}
    for mname, fn in methods.items():
        if mname == "__init__":
            continue
        domains = info.domains.get(mname) or set()
        if not domains:
            continue
        for attr, kind, _line, locked in _attr_accesses(fn):
            if kind == "write" and not locked:
                out.setdefault(attr, set()).update(domains)
    return out


def _cross_object(session: ProjectSession, tm: ThreadModel,
                  info: ClassThreads) -> List[Finding]:
    methods = info.module.methods(info.cls)
    out: List[Finding] = []
    written_cache: Dict[str, Dict[str, Set[str]]] = {}
    for mname, fn in methods.items():
        domains = info.domains.get(mname) or set()
        if not domains or mname == "__init__":
            continue
        types, constructed = _local_types(session, info, fn)
        locked = _locked_ids(fn)
        seen: Set[Tuple[str, str, str]] = set()
        for node in ast.walk(fn):
            target: Optional[ast.Attribute] = None
            kind = ""
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Attribute):
                target, kind = node.func, "call"
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and self_attr(t) is None:
                        target, kind = t, "write"
            elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load) and self_attr(node) is None:
                target, kind = node, "read"
            if target is None or id(node) in locked:
                continue
            base = _base_key(target.value)
            if base is None:
                continue
            cls2_name = types.get(base)
            if cls2_name is None and base.startswith("self."):
                cls2_name = _name_fallback(session, base[5:])
            elif cls2_name is None and not base.startswith("self."):
                cls2_name = None  # bare locals need explicit inference
            if cls2_name is None or cls2_name == info.cls.name:
                continue
            if base in constructed:
                continue
            info2 = tm.resolve(cls2_name)
            if info2 is None or not info2.all_domains():
                continue
            attr = target.attr
            key = (base, attr, kind)
            if key in seen:
                continue
            seen.add(key)
            if _is_lockish(attr) or attr in info2.channel_attrs:
                continue
            if kind == "call":
                d2 = info2.domains.get(attr) or set()
                if d2 and not (d2 & domains):
                    out.append(Finding(
                        path=info.module.path, line=node.lineno, code=_CODE,
                        message=(
                            f"{info.cls.name}.{mname} ({_fmt(domains)}) "
                            f"calls {cls2_name}.{attr} which runs under "
                            f"{_fmt(d2)} — cross to a foreign thread "
                            f"domain through its ring/queue, not a "
                            f"direct call"
                        ),
                        symbol=f"{info.cls.name}.{mname}.{base}.{attr}",
                    ))
            elif kind == "write":
                out.append(Finding(
                    path=info.module.path, line=node.lineno, code=_CODE,
                    message=(
                        f"{info.cls.name}.{mname} ({_fmt(domains)}) "
                        f"writes {base}.{attr} owned by {cls2_name} "
                        f"({_fmt(info2.all_domains())}) — foreign-domain "
                        f"state must be reached by message, not "
                        f"assignment"
                    ),
                    symbol=f"{info.cls.name}.{mname}.{base}.{attr}",
                ))
            else:  # read
                if cls2_name not in written_cache:
                    written_cache[cls2_name] = _domain_written_attrs(info2)
                wdoms = written_cache[cls2_name].get(attr) or set()
                if wdoms and not (wdoms & domains):
                    out.append(Finding(
                        path=info.module.path, line=node.lineno, code=_CODE,
                        message=(
                            f"{info.cls.name}.{mname} ({_fmt(domains)}) "
                            f"reads {base}.{attr}, which {cls2_name} "
                            f"writes from {_fmt(wdoms)} — an unlocked "
                            f"cross-thread read of mutable state"
                        ),
                        symbol=f"{info.cls.name}.{mname}.{base}.{attr}",
                    ))
    return out


@register_project(_CODE, "thread-ownership")
def check(session: ProjectSession) -> List[Finding]:
    tm = session.threads()
    out: List[Finding] = []
    for _name, info in sorted(tm.classes.items()):
        out.extend(_intra_class(info))
        out.extend(_cross_object(session, tm, info))
    return out
