"""GL002 — narrow except in a daemon reactor loop.

The hub ``_handle_disconnect`` bug class: a long-running ``while`` loop
on a thread does its per-iteration work under ``try ... except
(EOFError, OSError):``, and the handler itself performs fallible work
(e.g. connection cleanup). Any exception type outside the tuple — or
raised *by* the handler — escapes the loop and silently kills the
daemon thread, taking the whole control plane with it.

The checker flags, inside functions used as ``threading.Thread``
targets:

- a ``try`` nested in a long-running ``while`` loop, **and**
- a ``try`` whose body *contains* such a loop (the loop-inside-try
  shape),

when no handler can catch ``Exception`` and at least one narrow handler
does real work (contains a call outside a ``raise``). Handlers that are
pure control flow (``break`` / ``continue`` / ``pass`` / ``return`` /
``raise``) are idiomatic signals (``except queue.Empty: break``) and
are not flagged.

Fix shape: add an ``except Exception:`` arm that logs and keeps the
loop (or performs last-resort cleanup), and make the narrow handler's
work itself non-throwing.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import FileContext, Finding, qualname_map, register, walk_local

_BROAD = {"Exception", "BaseException"}


def _thread_targets(tree: ast.Module) -> Set[str]:
    """Names of functions passed as ``target=`` to a Thread() call
    anywhere in the module (bare names and ``self.x`` attributes)."""
    out: Set[str] = set()
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        fname = None
        if isinstance(n.func, ast.Attribute):
            fname = n.func.attr
        elif isinstance(n.func, ast.Name):
            fname = n.func.id
        if fname != "Thread":
            continue
        for kw in n.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Name):
                out.add(v.id)
            elif isinstance(v, ast.Attribute):
                out.add(v.attr)
    return out


def _long_running(test: ast.AST) -> bool:
    """``while True`` / ``while self._running`` / ``while not done``-style
    conditions: no bounded iteration, the loop lives as long as the
    thread does."""
    if isinstance(test, ast.Constant) and test.value is True:
        return True
    if isinstance(test, (ast.Name, ast.Attribute)):
        return True
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return isinstance(test.operand, (ast.Name, ast.Attribute))
    return False


def _has_broad_handler(try_node: ast.Try) -> bool:
    for h in try_node.handlers:
        if h.type is None:
            return True  # bare except
        elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for e in elts:
            name = e.id if isinstance(e, ast.Name) else (
                e.attr if isinstance(e, ast.Attribute) else None
            )
            if name in _BROAD:
                return True
    return False


def _handler_does_work(try_node: ast.Try) -> bool:
    """True if some handler body contains a call outside a ``raise``
    statement — i.e. work that can itself raise and escape."""
    for h in try_node.handlers:
        for stmt in h.body:
            if isinstance(stmt, ast.Raise):
                continue
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    return True
    return False


def _finding(ctx: FileContext, fn: ast.FunctionDef, try_node: ast.Try,
             shape: str, qual: str) -> Finding:
    return Finding(
        path=ctx.path,
        line=try_node.lineno,
        code="GL002",
        message=(
            f"narrow `except` {shape} the long-running loop of thread "
            f"target `{fn.name}` does fallible cleanup — a stray "
            f"exception kills the daemon thread; add an `except "
            f"Exception:` arm (log + drop the connection, never the "
            f"loop)"
        ),
        symbol=qual,
    )


@register("GL002", "narrow-except-in-reactor-loop")
def check(ctx: FileContext) -> List[Finding]:
    targets = _thread_targets(ctx.tree)
    if not targets:
        return []
    out: List[Finding] = []
    seen: Set[int] = set()
    quals = qualname_map(ctx.tree)
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.FunctionDef) or fn.name not in targets:
            continue
        qual = quals.get(id(fn), fn.name)
        loops = [
            n for n in walk_local(fn)
            if isinstance(n, ast.While) and _long_running(n.test)
        ]
        for loop in loops:
            for n in walk_local(loop):
                if (
                    isinstance(n, ast.Try)
                    and id(n) not in seen
                    and not _has_broad_handler(n)
                    and _handler_does_work(n)
                ):
                    seen.add(id(n))
                    out.append(_finding(ctx, fn, n, "inside", qual))
        # loop-inside-try: the try wraps the loop from outside
        for n in walk_local(fn):
            if not isinstance(n, ast.Try) or id(n) in seen:
                continue
            body_ids = {id(s) for stmt in n.body for s in ast.walk(stmt)}
            if (
                any(id(loop) in body_ids for loop in loops)
                and not _has_broad_handler(n)
                and _handler_does_work(n)
            ):
                seen.add(id(n))
                out.append(_finding(ctx, fn, n, "wrapping", qual))
    return out
