"""GL017 — deadline conformance in the serve plane.

PR 15 removed the serve plane's literal 60s/30s waits in favor of
deadline-derived timeouts: every blocking wait computes its bound from
``request_meta``'s deadline (``_remaining_s()`` and friends), so a
request either finishes inside its budget or fails fast — it never
parks a replica thread for a hard-coded interval that ignores how much
budget the caller has left.

This pass keeps that contract: inside ``ray_tpu/serve/``, a blocking
wait (``result``, ``wait``, ``asyncio.wait_for``, ``get``, ``acquire``,
``join``) whose timeout is a positive numeric **literal** is a finding.
The fix is to derive the bound from the request deadline; genuinely
request-independent waits (startup gates, shutdown drains) carry an
inline ``# graftlint: disable=GL017 — why`` justification instead.

Zero timeouts are exempt (``timeout=0`` is a poll, not a wait), as is
positional ``.get(...)`` (that shape is overwhelmingly ``dict.get``).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from ..core import Finding, register_project
from ..project import ProjectSession, _call_name, _functions_in, _local_nodes

_WAIT_TAILS = frozenset(
    {"result", "wait", "wait_for", "get", "acquire", "join"}
)
# calls where a bare positional numeric is the timeout
_POSITIONAL_ARG0 = frozenset({"result", "wait", "join", "acquire"})
_TIMEOUT_KWARGS = frozenset({"timeout", "timeout_s"})


def _serve_path(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    return "serve" in parts and "ray_tpu" in parts


def _positive_literal(node: ast.AST) -> Optional[float]:
    if (
        isinstance(node, ast.Constant)
        and isinstance(node.value, (int, float))
        and not isinstance(node.value, bool)
        and node.value > 0
    ):
        return float(node.value)
    return None


def _literal_timeout(call: ast.Call, tail: str) -> Optional[float]:
    for kw in call.keywords:
        if kw.arg in _TIMEOUT_KWARGS:
            return _positive_literal(kw.value)
    if tail in _POSITIONAL_ARG0 and call.args:
        return _positive_literal(call.args[0])
    if tail == "wait_for" and len(call.args) >= 2:
        return _positive_literal(call.args[1])
    return None


@register_project("GL017", "deadline-conformance")
def check(session: ProjectSession) -> List[Finding]:
    out: List[Finding] = []
    for mod in session.modules:
        if not _serve_path(mod.path):
            continue
        for fn in _functions_in(mod.ctx.tree):
            qual = mod.qualnames.get(id(fn), fn.name)
            # local walk: nested defs are visited as their own fn, so
            # each call is attributed to exactly one qualname
            for node in _local_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                tail = _call_name(node)
                if tail not in _WAIT_TAILS:
                    continue
                secs = _literal_timeout(node, tail)
                if secs is None:
                    continue
                out.append(
                    Finding(
                        path=mod.path,
                        line=node.lineno,
                        code="GL017",
                        message=(
                            f"`{tail}(...)` in `{qual}` waits a literal "
                            f"{secs:g}s instead of a deadline-derived "
                            f"bound — compute the timeout from the request "
                            f"deadline (request_meta) so the wait respects "
                            f"the caller's remaining budget, or justify "
                            f"with an inline disable"
                        ),
                        symbol=f"{qual}.{tail}.literal_timeout",
                    )
                )
    return out
