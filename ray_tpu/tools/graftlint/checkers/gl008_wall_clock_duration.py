"""GL008 — wall-clock delta used as a duration.

``time.time()`` steps with NTP adjustments and leap smearing; a
difference of two wall-clock reads is NOT a duration. Inside the
runtime core (``ray_tpu/_private/``) every interval measurement —
handler latency, queue wait, deadline arithmetic — must come from
``time.monotonic()`` / ``time.perf_counter()``. The task-lifecycle
stamps keep both: wall stamps position timeline slices in absolute
time, monotonic twins feed every subtraction.

The checker flags a subtraction (``a - b``) where either operand is
wall-derived — a direct ``time.time()`` call, or a local name whose
assignment contains one (including ``x = ev.get("t") or time.time()``)
— scoped to files under ``_private/`` PLUS ``ray_tpu/util/tracing.py``:
tracing is runtime infrastructure whose span durations feed the
critical-path analyzer (it anchors wall time once per process and
derives every interval from monotonic stamps — this rule keeps a
wall-delta duration from regressing in). Other user-facing code
(usage timestamps, display stamps) legitimately carries wall time.

Exception: an operand derived from file mtimes (``os.path.getmtime``,
``os.stat``/``os.fstat``, ``.st_mtime``) exempts the subtraction —
mtimes ARE wall clock, so comparing them against ``time.time()`` is
the only correct spelling (e.g. the runtime-env stale-lock breaker).

Fix shape: stamp ``t0 = time.monotonic()`` (or ``perf_counter`` for
sub-ms intervals) and subtract monotonic from monotonic.
"""

from __future__ import annotations

import ast
import os
from typing import List, Set

from ..core import (
    FileContext,
    Finding,
    dotted_name,
    qualname_map,
    register,
    walk_local,
)

_MTIME_CALLS = {
    "os.path.getmtime",
    "os.path.getctime",
    "os.path.getatime",
    "os.stat",
    "os.fstat",
    "posixpath.getmtime",
}


def _contains_wall_call(ctx: FileContext, node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            if ctx.resolve(dotted_name(n.func)) == "time.time":
                return True
    return False


def _is_mtime_derived(ctx: FileContext, node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            if ctx.resolve(dotted_name(n.func)) in _MTIME_CALLS:
                return True
        if isinstance(n, ast.Attribute) and n.attr in (
            "st_mtime", "st_ctime", "st_atime"
        ):
            return True
    return False


def _derived_names(ctx: FileContext, scope: ast.AST, contains) -> Set[str]:
    """Local names assigned from an expression satisfying `contains`
    (wall-clock and mtime provenance are tracked symmetrically, so an
    mtime stored in a local still exempts the subtraction)."""
    out: Set[str] = set()
    for n in walk_local(scope):
        value = None
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            value, targets = n.value, n.targets
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            value, targets = n.value, [n.target]
        elif isinstance(n, ast.AugAssign):
            value, targets = n.value, [n.target]
        if value is None or not contains(ctx, value):
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _matches(node: ast.AST, names: Set[str], contains, ctx) -> bool:
    if isinstance(node, ast.Name):
        return node.id in names
    return contains(ctx, node)


@register("GL008", "wall-clock-duration")
def check(ctx: FileContext) -> List[Finding]:
    norm = "/" + ctx.path.replace(os.sep, "/")
    if "/_private/" not in norm and not norm.endswith("/util/tracing.py"):
        return []
    out: List[Finding] = []
    quals = qualname_map(ctx.tree)
    scopes = [(ctx.tree, "<module>")]
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append((node, quals.get(id(node), node.name)))
    for scope, qual in scopes:
        wall = _derived_names(ctx, scope, _contains_wall_call)
        mtime = _derived_names(ctx, scope, _is_mtime_derived)
        for n in walk_local(scope):
            if not (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub)):
                continue
            left_wall = _matches(n.left, wall, _contains_wall_call, ctx)
            right_wall = _matches(n.right, wall, _contains_wall_call, ctx)
            if not (left_wall or right_wall):
                continue
            if _matches(n.left, mtime, _is_mtime_derived, ctx) or _matches(
                n.right, mtime, _is_mtime_derived, ctx
            ):
                continue  # comparing against file mtimes IS wall clock
            out.append(
                Finding(
                    path=ctx.path,
                    line=n.lineno,
                    code="GL008",
                    message=(
                        "time.time() delta used as a duration — wall "
                        "clock steps with NTP; stamp time.monotonic()/"
                        "perf_counter() and subtract those instead"
                    ),
                    symbol=qual,
                )
            )
    return out
