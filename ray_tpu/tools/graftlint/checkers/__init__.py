"""Checker registry: importing this package registers every checker
with :func:`ray_tpu.tools.graftlint.core.register`. Add a new rule by
dropping a module here and importing it below (see README.md)."""

from . import (  # noqa: F401
    gl001_lock_discipline,
    gl002_reactor_except,
    gl003_blocking_async,
    gl004_remote_misuse,
    gl005_unbounded_accumulator,
    gl006_accumulator_init,
    gl007_reflection_dispatch,
    gl008_wall_clock_duration,
    gl009_unbounded_registry,
    gl010_cross_shard_state,
    gl011_retry_without_backoff,
    gl012_protocol_conformance,
    gl013_thread_ownership,
    gl014_lock_order,
    gl015_async_discipline,
    gl016_resource_lifecycle,
    gl017_deadline_conformance,
    gl018_invariant_reserialization,
)
