"""GL004 — remote-API misuse.

Three sub-rules over the ``.remote()`` / ``ray_tpu.get`` surface:

1. **discarded ObjectRef** — an expression statement that is a bare
   ``x.remote(...)`` call throws its ObjectRef away: errors are never
   observed and the task's return value is pinned until ownership GC
   guesses. Keep the ref (``_ = ...`` at minimum) or ``get``/``wait``
   it.

2. **get-of-fresh-ref in a loop** — ``ray_tpu.get(f.remote(...))``
   inside a ``for``/``while`` loop *or comprehension* serializes what
   the API exists to parallelize: each iteration blocks on its own
   round-trip. Submit the whole batch first, then ``get`` the list
   once (``get`` of a *list comprehension* of refs is the good pattern
   and is not flagged).

3. **unserializable argument** — passing a lock/socket/file (or a
   ``self._lock``-style attribute) into ``.remote(...)``: the argument
   is pickled to another process, which either fails at call time or —
   worse — silently gives the worker a *different* lock.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..core import FileContext, Finding, dotted_name, register, self_attr

_GET_BASES = {"ray", "ray_tpu"}
_LOCK_HINTS = ("lock", "mutex", "cond", "cv", "sock", "conn")
_UNSERIALIZABLE_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "socket.socket", "socket.create_connection",
}


def _is_get_call(ctx: FileContext, call: ast.Call) -> bool:
    name = ctx.resolve(dotted_name(call.func))
    if not name or "." not in name:
        return False
    base, _, rest = name.rpartition(".")
    return rest == "get" and base.split(".")[0] in _GET_BASES


def _is_remote_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "remote"
    )


def _scope_name(stack: List[str]) -> str:
    return ".".join(stack) or "<module>"


@register("GL004", "remote-api-misuse")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []

    def visit(node: ast.AST, scope: List[str], loop_depth: int,
              lock_locals: Dict[str, str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, scope + [child.name], 0, {})
                continue
            if isinstance(child, ast.ClassDef):
                visit(child, scope + [child.name], 0, {})
                continue
            if isinstance(child, ast.Lambda):
                continue

            # track locals bound to known-unserializable constructors
            if isinstance(child, ast.Assign) and isinstance(child.value, ast.Call):
                ctor = ctx.resolve(dotted_name(child.value.func))
                if ctor in _UNSERIALIZABLE_CTORS:
                    for t in child.targets:
                        if isinstance(t, ast.Name):
                            lock_locals[t.id] = ctor

            # rule 1: discarded ObjectRef
            if isinstance(child, ast.Expr) and _is_remote_call(child.value):
                out.append(
                    Finding(
                        path=ctx.path,
                        line=child.lineno,
                        code="GL004",
                        message=(
                            "ObjectRef from `.remote(...)` is discarded — "
                            "task errors are never observed; keep the ref "
                            "and `get`/`wait` it (or bind it explicitly)"
                        ),
                        symbol=f"{_scope_name(scope)}.discarded",
                    )
                )

            if isinstance(child, ast.Call):
                # rule 2: get of a ref created in this same loop body
                if loop_depth > 0 and _is_get_call(ctx, child):
                    args = child.args
                    if args and _is_remote_call(args[0]):
                        out.append(
                            Finding(
                                path=ctx.path,
                                line=child.lineno,
                                code="GL004",
                                message=(
                                    "`get(x.remote(...))` inside a loop "
                                    "serializes the remote calls — submit "
                                    "all refs first, then `get` the list "
                                    "once"
                                ),
                                symbol=f"{_scope_name(scope)}.get_in_loop",
                            )
                        )
                # rule 3: unserializable args to .remote(...) —
                # keyword arguments pickle the same way positionals do
                if _is_remote_call(child):
                    for arg in list(child.args) + [
                        kw.value for kw in child.keywords
                    ]:
                        bad: Optional[str] = None
                        a = self_attr(arg)
                        if a is not None and any(
                            h in a.lower() for h in _LOCK_HINTS
                        ):
                            bad = f"self.{a}"
                        elif (
                            isinstance(arg, ast.Name)
                            and arg.id in lock_locals
                        ):
                            bad = f"{arg.id} ({lock_locals[arg.id]}())"
                        elif isinstance(arg, ast.Call):
                            ctor = ctx.resolve(dotted_name(arg.func))
                            if ctor in _UNSERIALIZABLE_CTORS:
                                bad = f"{ctor}()"
                        if bad is not None:
                            out.append(
                                Finding(
                                    path=ctx.path,
                                    line=child.lineno,
                                    code="GL004",
                                    message=(
                                        f"`{bad}` passed to `.remote(...)` "
                                        f"— locks/sockets don't pickle "
                                        f"(or arrive as a disconnected "
                                        f"copy); pass plain data and "
                                        f"rebuild the handle worker-side"
                                    ),
                                    symbol=(
                                        f"{_scope_name(scope)}.unserializable"
                                    ),
                                )
                            )

            # a comprehension's element expression runs once per item,
            # so `[get(f.remote(x)) for x in xs]` serializes exactly
            # like the for-loop spelling
            entered_loop = isinstance(
                child,
                (ast.For, ast.While, ast.AsyncFor,
                 ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
            )
            visit(child, scope, loop_depth + (1 if entered_loop else 0),
                  lock_locals)

    visit(ctx.tree, [], 0, {})
    return out
