"""GL010 — cross-shard / state-service attribute access from reactor code.

The multi-reactor hub (ray_tpu/_private/hub_shards.py) splits the
control plane into reactor shards (threads owning sockets + wire codec)
and single-thread-owned state services (scheduler+fairsched, object
directory) living behind the state plane.  The whole design rests on
one invariant: **reactor code never touches hub/service/peer-shard
mutable state directly** — everything crosses the boundary as a message
on an SPSC ring.  One stray ``self.hub.objects[oid] = ...`` from a
shard thread reintroduces exactly the data races the split exists to
remove, and it does so silently (the GIL makes most such races rare
enough to pass tests and corrupt state in production).

The checker flags, inside methods of reactor classes (class name
containing ``Shard`` or ``Reactor`` — the repo's reactor-code marker),
any attribute read or write whose base resolves to a hub / state-plane
/ service / peer-shard reference (``self.hub.x``, ``hub.x``,
``self.peers[i].x``, or a local alias assigned from one), unless the
accessed attribute is part of the message-queue API allow-list
(``push``/``drain``/``adopt``/``post``/``wake``/``stop``/``idx`` —
the ring and shard control surface, all single-writer safe).

Ring/stat containers the shard itself owns (``self._state_ring``,
``self.outbound``, ``self.stats``) are not banned bases: ownership is
the point, not indirection for its own sake.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from ..core import FileContext, Finding, register

_REACTOR_CLASS = re.compile(r"(Shard|Reactor)")

# object families reactor code must only reach by message
BANNED_BASES = {
    "hub", "state", "state_plane", "service", "services",
    "scheduler_service", "object_service", "object_directory",
    "shard", "shards", "peer", "peers",
}
# the message-queue / control API (single-writer-safe by construction)
ALLOWED_ATTRS = {"push", "drain", "adopt", "post", "wake", "stop", "idx"}


def _base_name(node: ast.AST) -> str:
    """Innermost meaningful base identifier of an attribute access:
    ``self.hub`` -> "hub", ``hub`` -> "hub", ``self.peers[i]`` ->
    "peers", anything else -> ""."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr
        return ""
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _banned_locals(fn: ast.AST, banned: Set[str]) -> Set[str]:
    """Names assigned from a banned base alias it:
    ``target = self.peers[i]`` makes ``target`` banned too."""
    out = set(banned)
    changed = True
    while changed:  # tiny fixpoint: aliases of aliases
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(
                node.value, (ast.Attribute, ast.Subscript, ast.Name)
            ):
                continue
            if _base_name(node.value) not in out:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id not in out:
                    out.add(tgt.id)
                    changed = True
    return out


@register("GL010", "cross-shard-state-access")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        if not _REACTOR_CLASS.search(cls.name):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            banned = _banned_locals(fn, BANNED_BASES)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Attribute):
                    continue
                base = _base_name(node.value)
                if base in banned and node.attr not in ALLOWED_ATTRS:
                    out.append(
                        Finding(
                            path=ctx.path,
                            line=node.lineno,
                            code="GL010",
                            message=(
                                f"reactor code touches {base}.{node.attr} "
                                "directly — shards must reach hub/service/"
                                "peer-shard state via the message ring "
                                "(push/post/adopt), never shared "
                                "attributes; see hub_shards.py"
                            ),
                            symbol=f"{cls.name}.{fn.name}.{base}.{node.attr}",
                        )
                    )
    return out
