"""GL001 — lock discipline.

Infers a "guarded-by" relation per class: any ``self.<attr>`` that is
*written* inside a ``with self._lock:`` block anywhere in the class is
considered guarded by that lock. Two violations are reported:

1. **unguarded write** — a write (assignment, ``+=``, subscript store,
   or mutating method call like ``.append``/``.pop``) to a guarded
   attribute outside any lock block, in a method other than
   ``__init__`` (construction happens-before sharing).

2. **split check-then-act** — the ``object_store.free()`` bug class: a
   local computed *from guarded attributes* under one lock acquisition
   gates (via ``if``) a *second* lock acquisition that writes those same
   attributes without re-validating them. Between the two acquisitions
   another thread may invalidate the check, e.g. a byte-cap test that
   two concurrent frees both pass::

       with self._lock:
           room = self._pool_bytes + cap <= MAX     # check
       if room:
           with self._lock:
               self._pool_bytes += cap              # act — cap exceeded

   The safe shape re-checks under the *same* acquisition that acts.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import FileContext, Finding, register, self_attr, walk_local

_LOCK_HINTS = ("lock", "mutex", "cond", "cv")
_MUTATORS = {
    "append", "extend", "insert", "add", "pop", "popleft", "popitem",
    "remove", "discard", "clear", "update", "setdefault", "appendleft",
    "move_to_end",
}


def _is_lock_attr(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in _LOCK_HINTS)


def _lock_with(node: ast.AST) -> bool:
    """True for ``with self._lock:`` / ``async with self._cv:`` blocks."""
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        attr = self_attr(item.context_expr)
        if attr is not None and _is_lock_attr(attr):
            return True
    return False


def _attr_writes(node: ast.AST) -> List[Tuple[str, int]]:
    """(attr, line) for every write to a ``self.<attr>`` under node."""
    return [
        w for n in walk_local(node) for w in _attr_writes_shallow(n)
    ]


def _attr_reads(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in walk_local(node):
        a = self_attr(n)
        if a is not None and isinstance(getattr(n, "ctx", None), ast.Load):
            out.add(a)
    return out


def _methods(cls: ast.ClassDef):
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield item


def _guarded_attrs(cls: ast.ClassDef) -> Set[str]:
    guarded: Set[str] = set()
    for fn in _methods(cls):
        for n in ast.walk(fn):
            if _lock_with(n):
                for attr, _line in _attr_writes(n):
                    if not _is_lock_attr(attr):
                        guarded.add(attr)
    return guarded


def _locked_node_ids(fn: ast.AST) -> Set[int]:
    ids: Set[int] = set()
    for n in ast.walk(fn):
        if _lock_with(n):
            for sub in ast.walk(n):
                ids.add(id(sub))
    return ids


def _unguarded_writes(
    cls: ast.ClassDef, guarded: Set[str], path: str
) -> List[Finding]:
    out: List[Finding] = []
    for fn in _methods(cls):
        if fn.name == "__init__":
            continue
        locked = _locked_node_ids(fn)
        seen: Set[Tuple[str, int]] = set()
        for n in walk_local(fn):
            if id(n) in locked:
                continue
            for attr, line in _attr_writes_shallow(n):
                if attr in guarded and (attr, line) not in seen:
                    seen.add((attr, line))
                    out.append(
                        Finding(
                            path=path,
                            line=line,
                            code="GL001",
                            message=(
                                f"write to `self.{attr}` outside the lock "
                                f"that guards it elsewhere in "
                                f"`{cls.name}` — take the lock or move "
                                f"the attribute out of the guarded set"
                            ),
                            symbol=f"{cls.name}.{fn.name}.{attr}",
                        )
                    )
    return out


def _attr_writes_shallow(n: ast.AST) -> List[Tuple[str, int]]:
    """Writes attributable to exactly this node (no recursion), so the
    locked-region filter in _unguarded_writes is per-statement."""
    out: List[Tuple[str, int]] = []
    if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = n.targets if isinstance(n, ast.Assign) else [n.target]
        for t in targets:
            a = self_attr(t)
            if a is not None:
                out.append((a, n.lineno))
            if isinstance(t, ast.Subscript):
                a = self_attr(t.value)
                if a is not None:
                    out.append((a, n.lineno))
    elif (
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr in _MUTATORS
    ):
        a = self_attr(n.func.value)
        if a is not None:
            out.append((a, n.lineno))
    elif isinstance(n, ast.Delete):
        for t in n.targets:
            if isinstance(t, ast.Subscript):
                a = self_attr(t.value)
                if a is not None:
                    out.append((a, n.lineno))
    return out


def _top_level_lock_blocks(fn: ast.AST) -> List[ast.AST]:
    """Lock blocks in source order, not nested inside another lock block."""
    blocks: List[ast.AST] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if _lock_with(child):
                blocks.append(child)
                continue  # don't descend: inner acquisitions are one region
            visit(child)

    visit(fn)
    blocks.sort(key=lambda b: b.lineno)
    return blocks


def _checked_locals(block: ast.AST, guarded: Set[str]) -> Dict[str, Set[str]]:
    """Locals assigned inside a lock block whose value reads guarded
    attributes: {local_name: {guarded attrs read}}."""
    out: Dict[str, Set[str]] = {}
    for n in walk_local(block):
        if isinstance(n, ast.Assign) and n.value is not None:
            reads = _attr_reads(n.value) & guarded
            if not reads:
                continue
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, set()).update(reads)
    return out


def _test_reads_name(test: ast.AST, name: str) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id == name:
            return True
    return False


def _block_retests(block: ast.AST, attrs: Set[str]) -> bool:
    """True if the block re-validates any of `attrs` under its own lock
    (an If/While/Assert/ternary test reading the attribute)."""
    for n in walk_local(block):
        test = None
        if isinstance(n, (ast.If, ast.While, ast.Assert, ast.IfExp)):
            test = n.test
        if test is not None and _attr_reads(test) & attrs:
            return True
    return False


def _split_check_then_act(
    cls: ast.ClassDef, guarded: Set[str], path: str
) -> List[Finding]:
    out: List[Finding] = []
    for fn in _methods(cls):
        blocks = _top_level_lock_blocks(fn)
        if len(blocks) < 2:
            continue
        for i, check_block in enumerate(blocks):
            checked = _checked_locals(check_block, guarded)
            if not checked:
                continue
            # gating ifs after the check block whose test uses a checked local
            for n in walk_local(fn):
                if not isinstance(n, ast.If) or n.lineno < check_block.lineno:
                    continue
                gating = [
                    (name, attrs)
                    for name, attrs in checked.items()
                    if _test_reads_name(n.test, name)
                ]
                if not gating:
                    continue
                body_ids = {
                    id(s) for stmt in n.body for s in ast.walk(stmt)
                }
                for act_block in blocks[i + 1:]:
                    if id(act_block) not in body_ids:
                        continue
                    acted = {a for a, _ in _attr_writes(act_block)}
                    for name, attrs in gating:
                        stale = acted & attrs
                        if stale and not _block_retests(act_block, stale):
                            out.append(
                                Finding(
                                    path=path,
                                    line=act_block.lineno,
                                    code="GL001",
                                    message=(
                                        f"check-then-act across two lock "
                                        f"acquisitions: `{name}` (line "
                                        f"{check_block.lineno}) checks "
                                        f"{_fmt(stale)} but this block "
                                        f"re-writes it without "
                                        f"re-validating — merge the check "
                                        f"and the write under one "
                                        f"acquisition"
                                    ),
                                    symbol=f"{cls.name}.{fn.name}",
                                )
                            )
                            break
    return out


def _fmt(attrs: Set[str]) -> str:
    return ", ".join(f"`self.{a}`" for a in sorted(attrs))


@register("GL001", "lock-discipline")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guarded = _guarded_attrs(node)
        if not guarded:
            continue
        out.extend(_unguarded_writes(node, guarded, ctx.path))
        out.extend(_split_check_then_act(node, guarded, ctx.path))
    return out
