"""GL006 — additive accumulator initialized to ones.

The ``NormalizeObservations._m2`` bug class: an attribute that is only
ever grown with ``+=`` (a running sum — Welford/Chan second moments,
counters, loss totals) but seeded with ``np.ones(...)`` instead of the
additive identity. The spurious +1 per element biases every early
estimate (e.g. std estimates read high until the count washes it out)
and the bug is invisible at convergence — exactly the kind of defect
tests on trained policies never catch.

Flags, per class: an ``Assign`` of ``*.ones(...)`` (numpy / jnp /
np.ones_like etc.) to a ``self.<attr>`` that some method accumulates
into with ``+=``. Seed additive accumulators with ``zeros``; if a
multiplicative or epsilon-floor seed is really intended, suppress the
line with ``# graftlint: disable=GL006`` and say why.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import FileContext, Finding, dotted_name, register, self_attr, walk_local


def _is_ones_call(node: ast.AST, ctx: FileContext) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = ctx.resolve(dotted_name(node.func))
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    return last in ("ones", "ones_like")


def _added_attrs(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in walk_local(fn):
            if isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Add):
                a = self_attr(n.target)
                if a is not None:
                    out.add(a)
    return out


@register("GL006", "accumulator-ones-init")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        added = _added_attrs(cls)
        if not added:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for n in walk_local(fn):
                if not isinstance(n, ast.Assign):
                    continue
                if not _is_ones_call(n.value, ctx):
                    continue
                for t in n.targets:
                    a = self_attr(t)
                    if a in added:
                        out.append(
                            Finding(
                                path=ctx.path,
                                line=n.lineno,
                                code="GL006",
                                message=(
                                    f"`self.{a}` is accumulated with "
                                    f"`+=` but seeded with `ones(...)` — "
                                    f"the additive identity is "
                                    f"`zeros(...)`; a ones seed biases "
                                    f"every early estimate"
                                ),
                                symbol=f"{cls.name}.{a}",
                            )
                        )
    return out
