"""GL007 — reflection dispatch in a message loop.

The hub ``_handle`` bug class: a reactor/dispatch loop resolves its
handler per message with ``getattr(obj, f"_on_{msg_type}")``. Every
message then pays an f-string build plus a dynamic attribute lookup —
pure overhead on the control plane's hottest path — and the handler
set is invisible to static analysis (a typo'd handler name silently
becomes "unknown message, drop").

The checker flags a ``getattr`` call whose *name* argument is built
dynamically from strings — an f-string (``ast.JoinedStr``), a
``"_on_" + x`` concatenation, a ``"_on_%s" % x`` format, or a
``"_on_{}".format(x)`` call — when the call sits inside a ``while`` or
``for`` loop. One-off reflection outside a loop (CLI subcommand
resolution, test helpers) is idiomatic and not flagged, as is a
constant name (``getattr(mod, "handler", None)``: a feature probe,
not per-message dispatch).

Fix shape: build a ``{msg_type: bound_method}`` dispatch table once at
construction time and do a dict lookup per message.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import FileContext, Finding, qualname_map, register, walk_local


def _is_dynamic_str(node: ast.AST) -> bool:
    """A string built per evaluation: f-string, str concat/format with a
    literal component, or "...".format(...). A plain Name/Attribute is
    NOT flagged (passing a precomputed name through getattr is the
    table pattern itself)."""
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Mod)):
        return any(
            isinstance(side, ast.Constant) and isinstance(side.value, str)
            or _is_dynamic_str(side)
            for side in (node.left, node.right)
        )
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "format"
        and isinstance(node.func.value, ast.Constant)
        and isinstance(node.func.value.value, str)
    ):
        return True
    return False


def _is_dynamic_getattr(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "getattr"
        and len(node.args) >= 2
        and _is_dynamic_str(node.args[1])
    )


@register("GL007", "reflection-dispatch-in-loop")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    quals = qualname_map(ctx.tree)
    seen = set()
    scopes = [(ctx.tree, "<module>")]
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append((node, quals.get(id(node), node.name)))
    for scope, qual in scopes:
        for loop in walk_local(scope):
            if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                continue
            for n in walk_local(loop):
                if _is_dynamic_getattr(n) and id(n) not in seen:
                    seen.add(id(n))
                    out.append(
                        Finding(
                            path=ctx.path,
                            line=n.lineno,
                            code="GL007",
                            message=(
                                "string-built getattr handler resolution "
                                "inside a loop — every iteration pays string "
                                "build + dynamic lookup; build a "
                                "{key: bound_method} dispatch table once "
                                "and index it"
                            ),
                            symbol=qual,
                        )
                    )
    return out
