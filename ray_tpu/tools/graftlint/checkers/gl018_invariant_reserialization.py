"""GL018 — per-call invariant re-serialization in a send loop.

The client-hot-path bug class PRs 12 and 18 removed twice: a submit
loop re-pickles the SAME value on every iteration — fn_id / resources /
options re-encoded per ``.remote()`` call, a template dict re-dumped
per task before ``send_bytes`` — when one encode hoisted above the
loop (or one cached opcode prefix, ``serialization.submit_frame_prefix``)
serves every iteration. At 10k calls/s the redundant encode is the
dominant client-side cost (bench_core ``submit_path_overhead``).

The checker flags a ``dumps``-family call (``dumps`` /
``dumps_frame`` / ``dumps_inline`` / ``dumps_function`` — covering
``pickle.dumps`` and ``cloudpickle.dumps`` through the attribute
spelling) inside a ``for``/``while`` loop in runtime-core code
(``_private/`` packages plus ``remote_function.py``) when

  1. the serialized expression mentions at least one variable (a bare
     literal is not "re-serializing an invariant" — it is just odd),
  2. every variable it mentions is LOOP-INVARIANT: plain names never
     bound inside the loop (for-targets, assignments, aug-assignments,
     walrus, ``with ... as``, ``except ... as``) and ``self.x``
     attributes never assigned inside the loop,
  3. the expression contains no call/comprehension/lambda/await (a
     nested call could produce a different value per iteration even
     from invariant inputs), AND
  4. the loop actually transmits — it contains a send-like call
     (``send`` / ``send_async`` / ``send_bytes`` / ``sendall`` /
     ``submit_task`` / ``submit_actor_task`` / ``request`` /
     ``publish``): encode-only loops (tests, codecs building corpora)
     are not the hot path this rule protects.

Fix shape: hoist the encode above the loop, or build a spliceable
template prefix once and hand-emit only the per-iteration fragment
(``serialization.submit_frame_prefix`` / ``task_entry_fragment``).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set, Tuple

from ..core import FileContext, Finding, qualname_map, register, self_attr, walk_local

_DUMPS_NAMES = {"dumps", "dumps_frame", "dumps_inline", "dumps_function"}
_SEND_ATTRS = {
    "send", "send_async", "send_bytes", "sendall",
    "submit_task", "submit_actor_task", "request", "publish",
}
# constructs inside the serialized expression that can yield a fresh
# value per iteration even from invariant inputs
_DYNAMIC_NODES = (
    ast.Call, ast.ListComp, ast.SetComp, ast.DictComp,
    ast.GeneratorExp, ast.Lambda, ast.Await, ast.Yield, ast.YieldFrom,
)


def _is_dumps_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in _DUMPS_NAMES
    if isinstance(fn, ast.Name):
        return fn.id in _DUMPS_NAMES
    return False


def _is_send_call(node: ast.Call) -> bool:
    fn = node.func
    return isinstance(fn, ast.Attribute) and fn.attr in _SEND_ATTRS


def _target_names(t: ast.AST) -> Set[str]:
    if isinstance(t, ast.Name):
        return {t.id}
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    if isinstance(t, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in t.elts:
            out |= _target_names(e)
        return out
    return set()


def _bound_in_loop(loop: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(plain names, self-attributes) bound anywhere inside the loop —
    including the loop's own iteration target and nested loops (but not
    nested function bodies, per walk_local)."""
    names: Set[str] = set()
    attrs: Set[str] = set()
    if isinstance(loop, ast.For):
        names |= _target_names(loop.target)

    def bind(t: ast.AST) -> None:
        names.update(_target_names(t))
        sa = self_attr(t)
        if sa is not None:
            attrs.add(sa)

    for n in walk_local(loop):
        if isinstance(n, ast.For):
            bind(n.target)
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                bind(t)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            bind(n.target)
        elif isinstance(n, ast.NamedExpr):
            bind(n.target)
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            bind(n.optional_vars)
        elif isinstance(n, ast.ExceptHandler) and n.name:
            names.add(n.name)
    return names, attrs


def _roots(expr: ast.AST) -> Optional[Tuple[Set[str], Set[str]]]:
    """(plain names, self-attributes) the expression reads, or None if
    it contains a dynamic construct (condition 3)."""
    names: Set[str] = set()
    attrs: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, _DYNAMIC_NODES):
            return None
        if isinstance(n, ast.Attribute):
            sa = self_attr(n)
            if sa is not None:
                attrs.add(sa)
        elif isinstance(n, ast.Name) and n.id not in ("self", "cls"):
            names.add(n.id)
    return names, attrs


@register("GL018", "invariant-reserialization")
def check(ctx: FileContext) -> List[Finding]:
    norm = "/" + ctx.path.replace(os.sep, "/")
    if "/_private/" not in norm and not norm.endswith("/remote_function.py"):
        return []
    out: List[Finding] = []
    quals = qualname_map(ctx.tree)
    fns = [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in fns:
        for loop in walk_local(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            calls = [
                n for n in walk_local(loop) if isinstance(n, ast.Call)
            ]
            if not any(_is_send_call(c) for c in calls):
                continue
            bound_names, bound_attrs = None, None
            for c in calls:
                if not (_is_dumps_call(c) and c.args):
                    continue
                roots = _roots(c.args[0])
                if roots is None:
                    continue  # dynamic expression: may vary per iteration
                names, attrs = roots
                if not names and not attrs:
                    continue  # pure literal (condition 1)
                if bound_names is None:
                    bound_names, bound_attrs = _bound_in_loop(loop)
                if names & bound_names or attrs & bound_attrs:
                    continue  # reads something the loop rebinds
                out.append(
                    Finding(
                        path=ctx.path,
                        line=c.lineno,
                        code="GL018",
                        message=(
                            "loop-invariant value re-serialized on "
                            "every iteration of a send loop: hoist the "
                            "encode above the loop (or cache a spliced "
                            "template prefix, serialization."
                            "submit_frame_prefix) instead of paying it "
                            "per call"
                        ),
                        symbol=quals.get(id(fn), fn.name),
                    )
                )
    return out
