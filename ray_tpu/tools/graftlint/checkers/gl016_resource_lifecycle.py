"""GL016 — resource lifecycle: acquire/release pairing with escape
analysis.

The runtime's hot paths hold four kinds of handles whose leaks are
silent until a node runs out of fds, mmaps, or wakes a dead timer:

- **mmap segment mappings** — ``MappedSegment`` / ``mmap`` /
  ``from_fd``; the object store's mapping table (``self._segments``)
  is the sanctioned owner, ``drop_mapping``/``free`` the drop side.
- **selectors** — every ``register`` needs an ``unregister`` path and
  the selector itself a ``close`` on teardown.
- **sockets** — ``socket(...)`` / ``create_connection(...)`` must be
  closed (or handed off) on every exit path.
- **one-shot timers and span records** — timers pushed onto a
  ``*timer*`` heap must be cleared on teardown; ``make_runtime_record``
  spans must be emitted or handed off.

Two layers, both over :meth:`ProjectSession.resources`:

*Class layer* — a class that registers selector fds but has no
unregister (or never closes the selector), pushes timers with no
teardown clear, or fills a handle registry it never drops from.

*Function layer (escape analysis)* — a local handle assigned from an
acquire constructor must be **resolved**: released
(``close``/``unmap``/…), transferred (stored into an attribute or
registry, passed to another call, returned/yielded, or used as a
context manager). No resolution at all is a leak. A call that can
raise strictly *between* the acquire and its first resolution is a
leak-on-raise finding — unless the acquire sits in a ``try`` with
cleanup (handlers/``finally``), the intervening call is infallible
(builtin allowlist), touches the handle itself, or lives on an
error-path span (``except``/``finally`` bodies).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..core import Finding, register_project
from ..project import (
    ACQUIRE_CTORS,
    RELEASE_METHODS,
    ProjectSession,
    _call_name,
    _functions_in,
)

# calls that cannot raise in a way worth modelling between acquire and
# release (attribute/arith errors there are programming bugs, not
# resource-pressure paths)
_INFALLIBLE = frozenset({
    "len", "isinstance", "issubclass", "id", "repr", "str", "int",
    "float", "bool", "min", "max", "abs", "round", "sorted", "list",
    "dict", "set", "tuple", "frozenset", "enumerate", "zip", "range",
    "getattr", "hasattr", "format", "print", "append", "debug", "info",
    "warning", "monotonic", "time", "perf_counter",
})


def _contains_name(node: ast.AST, name: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == name:
            return True
    return False


def _protected_spans(fn: ast.AST) -> List[Tuple[int, int]]:
    """Line spans of except-handler and finally bodies: calls there run
    on the error/cleanup path, not between acquire and release."""
    spans: List[Tuple[int, int]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        for part in list(node.handlers) + [node.finalbody, node.orelse]:
            stmts = part.body if isinstance(part, ast.ExceptHandler) else part
            if stmts:
                spans.append((
                    stmts[0].lineno,
                    max(getattr(s, "end_lineno", s.lineno) for s in stmts),
                ))
    return spans


def _try_wrapped(fn: ast.AST, line: int) -> bool:
    """True when ``line`` sits in the body of a try that has cleanup
    (handlers or finally) — the function already owns an error path."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        if not (node.handlers or node.finalbody):
            continue
        if node.body and (
            node.body[0].lineno
            <= line
            <= max(getattr(s, "end_lineno", s.lineno) for s in node.body)
        ):
            return True
    return False


def _acquires(fn: ast.AST) -> List[Tuple[str, str, int]]:
    """(handle name, resource kind, line) for local-only acquires.
    Multi-target assigns that also hit ``self.<attr>`` transfer
    ownership to the instance at the acquire itself — class layer."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or not isinstance(
            node.value, ast.Call
        ):
            continue
        kind = ACQUIRE_CTORS.get(_call_name(node.value) or "")
        if kind is None:
            continue
        if any(not isinstance(t, ast.Name) for t in node.targets):
            continue
        for t in node.targets:
            out.append((t.id, kind, node.lineno))
            break
    return out


def _first_resolution(fn: ast.AST, handle: str, after: int) -> Optional[int]:
    """Line of the first release/transfer of ``handle`` past the
    acquire, or None when the handle never escapes."""
    best: Optional[int] = None

    def note(line: int) -> None:
        nonlocal best
        if best is None or line < best:
            best = line

    for node in ast.walk(fn):
        line = getattr(node, "lineno", None)
        if line is None or line < after:
            continue
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in RELEASE_METHODS
                and _contains_name(f.value, handle)
            ):
                note(line)
            elif any(_contains_name(a, handle) for a in node.args) or any(
                _contains_name(kw.value, handle) for kw in node.keywords
            ):
                note(line)
        elif isinstance(node, ast.Assign):
            if any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                for t in node.targets
            ) and _contains_name(node.value, handle):
                note(line)
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            if node.value is not None and _contains_name(node.value, handle):
                note(line)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            if any(
                _contains_name(item.context_expr, handle)
                for item in node.items
            ):
                note(line)
    return best


@register_project("GL016", "resource-lifecycle")
def check(session: ProjectSession) -> List[Finding]:
    out: List[Finding] = []
    rm = session.resources()

    # ------------------------------------------------------- class layer
    for qual, rc in sorted(rm.classes.items()):
        path = rc.module.path
        if rc.register_sites and not rc.unregister_sites:
            out.append(
                Finding(
                    path=path,
                    line=min(rc.register_sites),
                    code="GL016",
                    message=(
                        f"`{qual}` registers fds on its selector but has no "
                        f"unregister path — dead connections keep their "
                        f"registration and the reactor spins on stale fds"
                    ),
                    symbol=f"{qual}.selector.unregister_missing",
                )
            )
        if rc.register_sites and not rc.selector_close_sites:
            out.append(
                Finding(
                    path=path,
                    line=min(rc.register_sites),
                    code="GL016",
                    message=(
                        f"`{qual}` never closes its selector — the epoll fd "
                        f"outlives teardown"
                    ),
                    symbol=f"{qual}.selector.close_missing",
                )
            )
        for attr, lines in sorted(rc.timer_attrs.items()):
            if attr in rc.timer_clears:
                continue
            out.append(
                Finding(
                    path=path,
                    line=min(lines),
                    code="GL016",
                    message=(
                        f"`{qual}` pushes one-shot timers onto "
                        f"`self.{attr}` but never clears it on teardown — "
                        f"pending timers fire into a dead runtime (clear "
                        f"the heap in the teardown path)"
                    ),
                    symbol=f"{qual}.{attr}.teardown_clear_missing",
                )
            )
        for attr, lines in sorted(rc.registry_attrs.items()):
            if attr in rc.registry_drops:
                continue
            out.append(
                Finding(
                    path=path,
                    line=min(lines),
                    code="GL016",
                    message=(
                        f"`{qual}` stores acquired handles into "
                        f"`self.{attr}` but never drops entries — the "
                        f"registry grows without bound and pins every "
                        f"mapping it holds"
                    ),
                    symbol=f"{qual}.{attr}.drop_missing",
                )
            )

    # ---------------------------------------------- function escape layer
    for mod in session.modules:
        for fn in _functions_in(mod.ctx.tree):
            qual = mod.qualnames.get(id(fn), fn.name)
            for handle, kind, line in _acquires(fn):
                resolved = _first_resolution(fn, handle, line)
                if resolved is None:
                    out.append(
                        Finding(
                            path=mod.path,
                            line=line,
                            code="GL016",
                            message=(
                                f"{kind} `{handle}` acquired in `{qual}` is "
                                f"never released or transferred — close it, "
                                f"store it in a tracked registry, or return "
                                f"it to the caller"
                            ),
                            symbol=f"{qual}.{handle}.unreleased",
                        )
                    )
                    continue
                if _try_wrapped(fn, line):
                    continue
                spans = _protected_spans(fn)
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    cl = node.lineno
                    if not (line < cl < resolved):
                        continue
                    if any(lo <= cl <= hi for lo, hi in spans):
                        continue
                    if _try_wrapped(fn, cl):
                        continue  # cleanup runs on raise — the fix shape
                    if _contains_name(node, handle):
                        continue
                    if (_call_name(node) or "") in _INFALLIBLE:
                        continue
                    out.append(
                        Finding(
                            path=mod.path,
                            line=cl,
                            code="GL016",
                            message=(
                                f"`{_call_name(node)}(...)` can raise "
                                f"between acquiring {kind} `{handle}` "
                                f"(line {line}) and its release/transfer "
                                f"(line {resolved}) in `{qual}` — wrap the "
                                f"gap in try/finally or acquire later"
                            ),
                            symbol=f"{qual}.{handle}.leak_on_raise",
                        )
                    )
                    break  # one finding per handle is enough
    return out
