"""GL003 — blocking call inside ``async def``.

The serve proxy and actor event loops run many requests on one thread;
a single synchronous sleep, file read, subprocess, or socket round-trip
inside a coroutine stalls *every* in-flight request on that loop (the
reference's "blocking call in asyncio loop" anti-pattern).

Flags calls to a known-blocking API inside an ``async def`` body
(nested sync ``def``s are excluded — they execute wherever they're
called). Resolution goes through the file's imports, so ``from time
import sleep`` / ``import subprocess as sp`` are caught too.

Fix shape: ``await asyncio.sleep(...)``, ``loop.run_in_executor(...)``,
or move the work to a worker thread before entering the coroutine.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import FileContext, Finding, dotted_name, qualname_map, register

_BLOCKING = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.call": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.check_call": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.check_output": "use `await asyncio.create_subprocess_exec(...)`",
    "os.system": "use `await asyncio.create_subprocess_shell(...)`",
    "os.popen": "use `await asyncio.create_subprocess_shell(...)`",
    "socket.create_connection": "use `await asyncio.open_connection(...)`",
    "urllib.request.urlopen": "use an async client or run_in_executor",
    "requests.get": "use an async client or run_in_executor",
    "requests.post": "use an async client or run_in_executor",
    "requests.put": "use an async client or run_in_executor",
    "requests.delete": "use an async client or run_in_executor",
    "requests.head": "use an async client or run_in_executor",
    "requests.request": "use an async client or run_in_executor",
    "open": "read via run_in_executor (sync file IO blocks the loop)",
}


def _async_body_calls(fn: ast.AsyncFunctionDef):
    """Calls lexically inside the coroutine (not nested sync defs)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # sync defs run wherever they're *called*; nested async
            # defs are visited by check() themselves — descending here
            # too would report their calls once per enclosing coroutine
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


@register("GL003", "blocking-call-in-async")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    quals = qualname_map(ctx.tree)
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        qual = quals.get(id(fn), fn.name)
        for call in _async_body_calls(fn):
            name = ctx.resolve(dotted_name(call.func))
            hint = _BLOCKING.get(name or "")
            if hint is None:
                continue
            out.append(
                Finding(
                    path=ctx.path,
                    line=call.lineno,
                    code="GL003",
                    message=(
                        f"blocking `{name}(...)` inside `async def "
                        f"{fn.name}` stalls every request on this event "
                        f"loop — {hint}"
                    ),
                    symbol=f"{qual}.{name}",
                )
            )
    return out
