"""GL003 — blocking call inside ``async def``.

The serve proxy and actor event loops run many requests on one thread;
a single synchronous sleep, file read, subprocess, or socket round-trip
inside a coroutine stalls *every* in-flight request on that loop (the
reference's "blocking call in asyncio loop" anti-pattern).

Flags calls to a known-blocking API inside an ``async def`` body
(nested sync ``def``s are excluded — they execute wherever they're
called). Resolution goes through the file's imports, so ``from time
import sleep`` / ``import subprocess as sp`` are caught too.

Two table shapes:

- ``BLOCKING``: dotted names resolved through imports (``time.sleep``);
- ``BLOCKING_METHODS``: the no-timeout *method* forms —
  ``Future.result()``, ``Event.wait()``, ``Queue.get()`` — which park
  the calling thread forever if the other side never shows up. These
  are receiver-typed: ``fut.result()`` only blocks when ``fut`` really
  is a future, so recognition pairs the method name with a local
  constructor scan plus receiver-name hints. Awaited calls are exempt
  (``await queue.get()`` on an ``asyncio.Queue`` is the fix shape, not
  the bug). The same tables seed the whole-program flow model's
  transitive-blocking roots (GL015), so a sync helper reaching one of
  these forms taints every coroutine that calls the helper.

Fix shape: ``await asyncio.sleep(...)``, ``loop.run_in_executor(...)``,
or move the work to a worker thread before entering the coroutine.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import FileContext, Finding, dotted_name, qualname_map, register

BLOCKING = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.call": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.check_call": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.check_output": "use `await asyncio.create_subprocess_exec(...)`",
    "os.system": "use `await asyncio.create_subprocess_shell(...)`",
    "os.popen": "use `await asyncio.create_subprocess_shell(...)`",
    "socket.create_connection": "use `await asyncio.open_connection(...)`",
    "urllib.request.urlopen": "use an async client or run_in_executor",
    "requests.get": "use an async client or run_in_executor",
    "requests.post": "use an async client or run_in_executor",
    "requests.put": "use an async client or run_in_executor",
    "requests.delete": "use an async client or run_in_executor",
    "requests.head": "use an async client or run_in_executor",
    "requests.request": "use an async client or run_in_executor",
    "open": "read via run_in_executor (sync file IO blocks the loop)",
}

# back-compat alias (the table predates the method-form growth)
_BLOCKING = BLOCKING

# no-timeout blocking method forms: method name -> (receiver kind, fix)
BLOCKING_METHODS: Dict[str, Tuple[str, str]] = {
    "result": ("future", "await the future, or pass a deadline-derived "
                         "timeout so a lost reply cannot park the thread"),
    "wait": ("event", "await an asyncio.Event, or pass a timeout and "
                      "re-check the condition"),
    "get": ("queue", "use asyncio.Queue + await get(), or pass a timeout"),
}

# constructor/factory trailing names -> receiver kind, for the local
# ctor scan (``fut = pool.submit(...)`` types ``fut`` as a future)
_CTOR_KINDS = {
    "Future": "future",
    "submit": "future",
    "run_coroutine_threadsafe": "future",
    "Event": "event",
    "Queue": "queue",
    "SimpleQueue": "queue",
    "LifoQueue": "queue",
    "PriorityQueue": "queue",
}

# receiver-name substrings typing self-attrs and parameters the ctor
# scan cannot see (``self._ready.wait()``)
_NAME_HINTS = {
    "future": ("fut", "promise"),
    "event": ("event", "evt", "ready", "stopped", "shutdown", "_stop",
              "done"),
    "queue": ("queue", "_q", "inbox", "outbox"),
}


def local_ctor_kinds(fn: ast.AST) -> Dict[str, str]:
    """name -> receiver kind for locals assigned from a recognized
    constructor/factory inside ``fn`` (nested defs excluded — their
    locals are not this function's)."""
    out: Dict[str, str] = {}
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            f = n.value.func
            tail = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            kind = _CTOR_KINDS.get(tail or "")
            if kind:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = kind
        stack.extend(ast.iter_child_nodes(n))
    return out


def _receiver_name(call: ast.Call) -> Optional[str]:
    """Trailing identifier of the method call's receiver:
    ``self._ready.wait()`` -> "_ready", ``q.get()`` -> "q"."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    base = f.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return None


def blocking_method_form(
    call: ast.Call, local_kinds: Dict[str, str]
) -> Optional[Tuple[str, str, str]]:
    """(receiver, kind, fix hint) when ``call`` is a no-timeout blocking
    method form (``fut.result()`` / ``evt.wait()`` / ``q.get()`` with no
    arguments at all — any argument may bound the wait)."""
    if call.args or call.keywords:
        return None
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in BLOCKING_METHODS:
        return None
    want_kind, hint = BLOCKING_METHODS[f.attr]
    recv = _receiver_name(call)
    if recv is None:
        return None
    kind = local_kinds.get(recv)
    if kind is None:
        low = recv.lower()
        for k, hints in _NAME_HINTS.items():
            if any(h in low for h in hints):
                kind = k
                break
    if kind != want_kind:
        return None
    return recv, kind, hint


def _async_body_calls(fn: ast.AsyncFunctionDef):
    """Calls lexically inside the coroutine (not nested sync defs)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # sync defs run wherever they're *called*; nested async
            # defs are visited by check() themselves — descending here
            # too would report their calls once per enclosing coroutine
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


@register("GL003", "blocking-call-in-async")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    quals = qualname_map(ctx.tree)
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        qual = quals.get(id(fn), fn.name)
        # every node under an Await: `await q.get()` is the asyncio
        # primitive, and `await asyncio.wait_for(q.get(), t)` hands the
        # coroutine to the scheduler — neither blocks the thread
        awaited = {
            id(sub)
            for n in ast.walk(fn)
            if isinstance(n, ast.Await)
            for sub in ast.walk(n)
        }
        local_kinds = local_ctor_kinds(fn)
        for call in _async_body_calls(fn):
            if id(call) in awaited:
                continue
            name = ctx.resolve(dotted_name(call.func))
            hint = BLOCKING.get(name or "")
            if hint is not None:
                out.append(
                    Finding(
                        path=ctx.path,
                        line=call.lineno,
                        code="GL003",
                        message=(
                            f"blocking `{name}(...)` inside `async def "
                            f"{fn.name}` stalls every request on this event "
                            f"loop — {hint}"
                        ),
                        symbol=f"{qual}.{name}",
                    )
                )
                continue
            form = blocking_method_form(call, local_kinds)
            if form is not None:
                recv, kind, fix = form
                method = call.func.attr
                out.append(
                    Finding(
                        path=ctx.path,
                        line=call.lineno,
                        code="GL003",
                        message=(
                            f"no-timeout `{recv}.{method}()` inside "
                            f"`async def {fn.name}` parks the event loop "
                            f"until the {kind} resolves — {fix}"
                        ),
                        symbol=f"{qual}.{recv}.{method}",
                    )
                )
    return out
