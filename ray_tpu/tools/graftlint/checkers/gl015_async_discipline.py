"""GL015 — whole-program async discipline.

GL003 sees a coroutine that blocks *directly*; it cannot see the three
shapes PRs 13–15 actually shipped bugs (or hand-fixes) for:

(a) an ``async def`` calling a **sync helper** that transitively —
    through the project call graph — reaches a known-blocking API
    (GL003's tables are the roots) or takes a lock that a non-loop
    thread holds around blocking work. The coroutine never says
    ``sleep`` itself, but the loop stalls all the same.
(b) a call to a project ``async def`` whose coroutine is neither
    awaited nor stored: the body silently never runs (Python only
    warns at GC time, and only with warnings enabled).
(c) a closure handed to ``run_in_executor`` / ``Thread(target=)`` from
    a function that reads the ambient trace contextvar
    (``current_context`` / ``begin_trace``) without re-pushing it via
    ``push_context``: executor threads do not inherit contextvars, so
    the span parentage PR 13 hand-restored silently drops again.
    ``asyncio.to_thread`` copies context and bound-method targets carry
    no ambient reads, so only local lambdas/nested defs are checked;
    an ``if <x> is None:`` guard marks the no-trace fast path exempt.

All three read the lazily built :meth:`ProjectSession.flow` model;
see ``project._build_flow_model`` for the resolution rules.
"""

from __future__ import annotations

from typing import List

from ..core import Finding, register_project
from ..project import ProjectSession


@register_project("GL015", "async-discipline")
def check(session: ProjectSession) -> List[Finding]:
    out: List[Finding] = []
    fm = session.flow()
    for key, ff in fm.functions.items():
        # ---- (c) context-dropping dispatches (sync or async callers)
        for line, closure in ff.ctx_unsafe_dispatches:
            out.append(
                Finding(
                    path=ff.module.path,
                    line=line,
                    code="GL015",
                    message=(
                        f"`{ff.qual}` reads the ambient trace context but "
                        f"dispatches `{closure}` to an executor/thread "
                        f"without re-pushing it (`push_context(...)` inside "
                        f"the closure) — executor threads do not inherit "
                        f"contextvars, so the span parent is silently lost"
                    ),
                    symbol=f"{ff.qual}.{closure}.ctx_dropped",
                )
            )
        if not ff.is_async:
            continue
        seen_blocking = set()
        for line, callee, under_await, is_stmt in ff.calls:
            target = fm.functions.get(callee)
            if target is None:
                continue
            # ---- (b) coroutine created, never awaited or stored
            if target.is_async and is_stmt and not under_await:
                out.append(
                    Finding(
                        path=ff.module.path,
                        line=line,
                        code="GL015",
                        message=(
                            f"`{ff.qual}` calls `async def {callee}` "
                            f"without awaiting or storing the coroutine — "
                            f"the body never runs; add `await` or keep the "
                            f"task (`asyncio.create_task`)"
                        ),
                        symbol=f"{ff.qual}.{callee}.never_awaited",
                    )
                )
                continue
            # ---- (a) sync helper that transitively blocks
            if target.is_async or under_await or callee in seen_blocking:
                continue
            chain = fm.blocking_chain(callee)
            if chain is None:
                continue
            seen_blocking.add(callee)
            out.append(
                Finding(
                    path=ff.module.path,
                    line=line,
                    code="GL015",
                    message=(
                        f"`async def {ff.qual.rsplit('.', 1)[-1]}` calls "
                        f"sync `{callee}`, which blocks the event loop via "
                        f"{' -> '.join(chain)} — await an async equivalent "
                        f"or move the call to `run_in_executor`"
                    ),
                    symbol=f"{ff.qual}.{callee}.blocking",
                )
            )
    return out
