"""GL012 — whole-program protocol conformance.

The reference encodes its wire contract in 21 checked ``.proto`` files;
ours is ``protocol.py`` string constants plus ``(msg_type, payload)``
dicts with **no compiler watching either side**. A typo'd payload key or
a handler nobody sends to isn't a build error here — it's a wedged
cluster at 2am. This pass rebuilds the message model the way a protobuf
compiler would, from the whole tree at once:

- **constants** from ``protocol.py``;
- **send sites**: ``_send``/``send``/``send_async``/``request``/
  ``_traced_send``/``_reply`` calls and raw ``dumps_frame((msg, p))``
  framing, with payload keys tracked through literal dicts, local
  augmentation (``payload["k"] = ...``) and ``dict(payload, k=...)``;
- **dispatch tables** in all three repo spellings: dict literals
  (``CoreClient._inbound_handlers``), the ``dir()``/``_on_`` convention
  table (``Hub._handlers``), and ``if/elif msg_type == P.X`` chains
  (node agent, worker main loop, object agent);
- **routing sets** (``SCHEDULER_MSGS``/``OBJECT_MSGS`` →
  ``SERVICE_OF``) for the sharded topology.

Findings:

1. *unregistered message string* — a send site or dispatch entry uses a
   message value no ``protocol.py`` constant defines (the contract file
   is THE catalog; a string that bypasses it is invisible to readers
   and to this pass's other checks);
2. *sent-but-unhandled* — a type some process sends that no dispatch
   table handles and no inline comparison consumes (the object plane's
   request/response replies are read inline, so ``mt != "obj_data"``
   counts as consumption);
3. *handled-but-never-sent* — dead dispatch surface, or a sender that
   was never written;
4. *topology divergence* — the single-reactor handler table
   (``Hub._handlers``) and the sharded routing sets must cover the
   IDENTICAL message set: a type missing from ``SERVICE_OF`` silently
   falls to the default service, a type only in ``SERVICE_OF`` is
   routed to a handler that doesn't exist;
5. *required payload key missing* — a key a handler reads by plain
   unconditional subscript is absent from some send site's tracked
   literal payload (``.get`` reads and reads under ``if`` are treated
   as optional);
6. *payload key never read* — a key every send site includes that no
   handler ever reads (dead wire weight), checked only when every
   handler's payload use is fully visible (no escapes/iteration);
7. *required item key missing* — vector payloads (bulk frames like
   ``SUBMIT_TASKS`` carrying ``tasks: [{...}, ...]``): a handler that
   loops ``for t in payload[k]`` and subscripts ``t["x"]``
   unconditionally requires ``x`` on EVERY item; a send site building
   the item list from tracked dict literals must include it.

The pass is inert in sessions without a ``protocol.py`` (single-file
fixture runs of other rules), so per-file checks stay per-file.
"""

from __future__ import annotations

from typing import Dict, List, Set

from ..core import Finding, register_project
from ..project import ProjectSession

_CODE = "GL012"


def _f(path: str, line: int, message: str, symbol: str) -> Finding:
    return Finding(path=path, line=line, code=_CODE, message=message,
                   symbol=symbol)


@register_project(_CODE, "protocol-conformance")
def check(session: ProjectSession) -> List[Finding]:
    pm = session.protocol()
    if pm.protocol_module is None or not pm.constants:
        return []
    out: List[Finding] = []
    sent = {s.msg for s in pm.sends}
    handled: Set[str] = set()
    for t in pm.tables:
        handled |= t.msgs

    # ---- 1. unregistered message strings
    for msg in sorted((sent | handled) - pm.constant_values):
        sites = pm.sends_of(msg)
        hs = pm.handlers_of(msg)
        if sites:
            anchor_path, anchor_line = sites[0].module.path, sites[0].line
        elif hs:
            anchor_path, anchor_line = hs[0].module.path, hs[0].line
        else:  # prefix-table entry with no method body found
            t = next(t for t in pm.tables if msg in t.msgs)
            anchor_path, anchor_line = t.module.path, t.line
        out.append(_f(
            anchor_path, anchor_line,
            f"message type {msg!r} is not defined in protocol.py — add a "
            f"constant (the protocol module is the wire contract; a bare "
            f"string bypasses it and every conformance check)",
            f"<protocol>.{msg}.unregistered",
        ))

    # ---- 2. sent but unhandled
    for msg in sorted(sent - handled - pm.compared):
        s = pm.sends_of(msg)[0]
        out.append(_f(
            s.module.path, s.line,
            f"message {msg!r} is sent here but no dispatch table handles "
            f"it and no receiver compares against it — a typo'd type or "
            f"a missing handler; the frame would be silently dropped",
            f"<protocol>.{msg}.unhandled",
        ))

    # ---- 3. handled but never sent
    for msg in sorted(handled - sent):
        hs = pm.handlers_of(msg)
        if hs:
            path, line, sym = hs[0].module.path, hs[0].line, hs[0].symbol
        else:
            t = next(t for t in pm.tables if msg in t.msgs)
            path, line, sym = t.module.path, t.line, t.owner
        out.append(_f(
            path, line,
            f"message {msg!r} has a handler ({sym}) but no send site "
            f"anywhere in the tree — dead dispatch surface, or the "
            f"sender was never wired up",
            f"<protocol>.{msg}.never_sent",
        ))

    # ---- 4. topology parity (single-reactor vs sharded routing)
    prefix_tables = [t for t in pm.tables if t.kind == "prefix"]
    routed: Set[str] = set()
    routed_anchor = None
    for r in pm.routing_sets:
        if r.sharded:
            routed |= r.msgs
            routed_anchor = routed_anchor or r
    if prefix_tables and routed_anchor is not None:
        hub_t = max(prefix_tables, key=lambda t: len(t.msgs))
        for msg in sorted(hub_t.msgs - routed):
            out.append(_f(
                routed_anchor.module.path, routed_anchor.line,
                f"message {msg!r} has a {hub_t.owner} handler but is "
                f"missing from the sharded routing sets — it would fall "
                f"to the default service implicitly; both topologies "
                f"must route the identical message set",
                f"<topology>.{msg}.unrouted",
            ))
        for msg in sorted(routed - hub_t.msgs):
            out.append(_f(
                routed_anchor.module.path, routed_anchor.line,
                f"message {msg!r} is routed by the sharded topology but "
                f"{hub_t.owner} has no handler for it — the single-"
                f"reactor hub would drop it; both topologies must cover "
                f"the identical message set",
                f"<topology>.{msg}.unhandled",
            ))

    # ---- 5./6. payload key conformance
    for msg in sorted(sent & handled):
        hs = pm.handlers_of(msg)
        ss = pm.sends_of(msg)
        if not hs or not ss:
            continue
        required: Dict[str, object] = {}
        for h in hs:
            for k in h.required_keys:
                required.setdefault(k, h)
        # ---- 7. vector payloads: per-item required keys
        item_required: Dict[object, object] = {}
        for h in hs:
            for pk, iks in h.item_required.items():
                for ik in iks:
                    item_required.setdefault((pk, ik), h)
        for s in ss:
            for (pk, ik), h in sorted(item_required.items()):
                iks = s.item_keys.get(pk)
                if iks is None or ik in iks:
                    # untracked item list = opaque (no claim either way)
                    continue
                out.append(_f(
                    s.module.path, s.line,
                    f"send site for {msg!r} builds {pk!r} items without "
                    f"key {ik!r} which {h.symbol} reads unconditionally "
                    f"on every item (for t in payload[{pk!r}]: "
                    f"t[{ik!r}]) — this send would KeyError in the "
                    f"handler",
                    f"{s.symbol}.{msg}.{pk}[].{ik}.missing",
                ))
        for s in ss:
            if s.keys is None:
                continue
            for k in sorted(set(required) - set(s.keys)):
                h = required[k]
                out.append(_f(
                    s.module.path, s.line,
                    f"send site for {msg!r} omits key {k!r} which "
                    f"{h.symbol} reads unconditionally "
                    f"(payload[{k!r}]) — this send would KeyError in "
                    f"the handler",
                    f"{s.symbol}.{msg}.{k}.missing",
                ))
        if any(h.opaque for h in hs) or any(s.keys is None for s in ss):
            continue
        read: Set[str] = set()
        for h in hs:
            read |= h.read_keys
        common = None
        for s in ss:
            common = set(s.keys) if common is None else common & set(s.keys)
        for k in sorted((common or set()) - read - {"req_id", "trace"}):
            s = ss[0]
            out.append(_f(
                s.module.path, s.line,
                f"payload key {k!r} of {msg!r} is produced by every send "
                f"site but never read by any handler "
                f"({', '.join(sorted({h.symbol for h in hs}))}) — dead "
                f"wire weight, or the read was lost in a refactor",
                f"<protocol>.{msg}.{k}.never_read",
            ))
    return out
