"""GL005 — unbounded steady-state accumulator.

The ``multi_agent.completed_returns`` leak class: an instance (or
module-level) list initialized empty, appended to *inside a loop* in a
steady-state method, and never trimmed, rotated, cleared, or
reassigned anywhere in the class. Every fragment/iteration grows it; a
long-running worker leaks without bound.

Reads don't save it: ``self.xs[-100:]`` keeps the window but still
retains the whole history. Fix shape::

    self.completed_returns = collections.deque(maxlen=100)

or trim explicitly (``del self.xs[:-100]``) where the window is
consumed.

Only append-in-a-loop sites are flagged: a list appended once per call
on a request path is usually a registry with an external lifecycle,
and flagging those drowns the signal. A growth site inside an ``if``
that tests the list itself (``if not _TABLE: ... append``) is a
build-once memo and is exempt — it converges, it doesn't accumulate.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import FileContext, Finding, register, self_attr, walk_local

_GROWERS = {"append", "extend", "insert", "appendleft"}
_TRIMMERS = {"pop", "popleft", "popitem", "remove", "clear", "__delitem__"}


def _empty_list(value: Optional[ast.AST]) -> bool:
    return isinstance(value, ast.List) and not value.elts


def _methods(cls: ast.ClassDef):
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield item


def _init_list_attrs(cls: ast.ClassDef) -> Set[str]:
    attrs: Set[str] = set()
    for fn in _methods(cls):
        if fn.name != "__init__":
            continue
        for n in walk_local(fn):
            if isinstance(n, ast.Assign) and _empty_list(n.value):
                for t in n.targets:
                    a = self_attr(t)
                    if a is not None:
                        attrs.add(a)
            elif isinstance(n, ast.AnnAssign) and _empty_list(n.value):
                a = self_attr(n.target)
                if a is not None:
                    attrs.add(a)
    return attrs


def _memo_guard_ids(root: ast.AST, attr_of) -> Dict[str, Set[int]]:
    """For each guarded name X: ids of nodes inside an ``if`` whose test
    reads X (the ``if not X: ... X.append`` build-once memo shape)."""
    out: Dict[str, Set[int]] = {}
    for n in walk_local(root):
        if not isinstance(n, ast.If):
            continue
        tested = {
            a for t in ast.walk(n.test)
            for a in [attr_of(t)] if a is not None
        }
        if not tested:
            continue
        ids = {id(s) for stmt in n.body for s in ast.walk(stmt)}
        for a in tested:
            out.setdefault(a, set()).update(ids)
    return out


def _classify_class(
    cls: ast.ClassDef, attrs: Set[str]
) -> Tuple[Dict[str, List[Tuple[str, int]]], Set[str]]:
    """(grow sites inside loops per attr, attrs that are ever trimmed
    or reassigned outside __init__)."""
    grows: Dict[str, List[Tuple[str, int]]] = {}
    bounded: Set[str] = set()
    for fn in _methods(cls):
        if fn.name == "__init__":
            continue
        memo = _memo_guard_ids(fn, self_attr)

        def visit(node: ast.AST, in_loop: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                il = in_loop or isinstance(
                    child, (ast.For, ast.While, ast.AsyncFor)
                )
                if (
                    isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                ):
                    a = self_attr(child.func.value)
                    if a in attrs:
                        if (
                            child.func.attr in _GROWERS
                            and il
                            and id(child) not in memo.get(a, ())
                        ):
                            grows.setdefault(a, []).append(
                                (fn.name, child.lineno)
                            )
                        elif child.func.attr in _TRIMMERS:
                            bounded.add(a)
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (
                        child.targets
                        if isinstance(child, ast.Assign)
                        else [child.target]
                    )
                    # expand tuple unpacking: `out, self.buf = self.buf, []`
                    targets = [
                        e
                        for t in targets
                        for e in (
                            t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t]
                        )
                    ]
                    for t in targets:
                        a = self_attr(t)
                        if a in attrs and isinstance(child, ast.Assign):
                            bounded.add(a)  # reassignment resets it
                        if isinstance(t, (ast.Subscript,)):
                            a = self_attr(t.value)
                            if a in attrs:
                                bounded.add(a)  # slice-assign can shrink
                if isinstance(child, ast.Delete):
                    for t in child.targets:
                        if isinstance(t, ast.Subscript):
                            a = self_attr(t.value)
                            if a in attrs:
                                bounded.add(a)
                visit(child, il)

        visit(fn, False)
    return grows, bounded


def _module_level(ctx: FileContext) -> List[Finding]:
    """Module-global empty lists appended in loops and never bounded."""
    globals_: Set[str] = set()
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and _empty_list(stmt.value):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    globals_.add(t.id)
        elif (
            isinstance(stmt, ast.AnnAssign)
            and _empty_list(stmt.value)
            and isinstance(stmt.target, ast.Name)
        ):
            globals_.add(stmt.target.id)
    if not globals_:
        return []
    grows: Dict[str, List[int]] = {}
    bounded: Set[str] = set()
    memo: Dict[str, Set[int]] = {}
    for n in ast.walk(ctx.tree):
        if not isinstance(n, ast.If):
            continue
        tested = {
            t.id for t in ast.walk(n.test)
            if isinstance(t, ast.Name) and t.id in globals_
        }
        if not tested:
            continue
        ids = {id(s) for stmt in n.body for s in ast.walk(stmt)}
        for name in tested:
            memo.setdefault(name, set()).update(ids)

    def visit(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            il = in_loop or isinstance(child, (ast.For, ast.While, ast.AsyncFor))
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and isinstance(child.func.value, ast.Name)
                and child.func.value.id in globals_
            ):
                if (
                    child.func.attr in _GROWERS
                    and il
                    and id(child) not in memo.get(child.func.value.id, ())
                ):
                    grows.setdefault(child.func.value.id, []).append(
                        child.lineno
                    )
                elif child.func.attr in _TRIMMERS:
                    bounded.add(child.func.value.id)
            if isinstance(child, (ast.Assign, ast.Delete)):
                for t in child.targets:
                    if isinstance(t, ast.Name) and t.id in globals_:
                        if child.col_offset > 0:  # rebinding inside a fn
                            bounded.add(t.id)
                    if isinstance(t, ast.Subscript) and isinstance(
                        t.value, ast.Name
                    ) and t.value.id in globals_:
                        bounded.add(t.value.id)
            visit(child, il)

    visit(ctx.tree, False)
    out: List[Finding] = []
    for name, lines in grows.items():
        if name in bounded:
            continue
        out.append(
            Finding(
                path=ctx.path,
                line=lines[0],
                code="GL005",
                message=(
                    f"module-level list `{name}` grows inside a loop and "
                    f"is never trimmed — long-running processes leak; "
                    f"bound it (deque(maxlen=...)) or rotate it"
                ),
                symbol=f"<module>.{name}",
            )
        )
    return out


@register("GL005", "unbounded-accumulator")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        attrs = _init_list_attrs(cls)
        if not attrs:
            continue
        grows, bounded = _classify_class(cls, attrs)
        for attr, sites in grows.items():
            if attr in bounded:
                continue
            meth, line = sites[0]
            out.append(
                Finding(
                    path=ctx.path,
                    line=line,
                    code="GL005",
                    message=(
                        f"`self.{attr}` grows inside a loop in "
                        f"`{cls.name}.{meth}` and is never trimmed, "
                        f"cleared, or reassigned — a long-lived instance "
                        f"leaks; use `collections.deque(maxlen=...)` or "
                        f"trim where the window is consumed"
                    ),
                    symbol=f"{cls.name}.{attr}",
                )
            )
    out.extend(_module_level(ctx))
    return out
