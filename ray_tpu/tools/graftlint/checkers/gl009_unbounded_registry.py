"""GL009 — unbounded registry growth in message handlers.

The hub-side leak class this repo grows around: a long-lived reactor
class keeps dict/list registries (``self.objects``, ``self.workers``,
``self.jobs``...) that message handlers insert into on every inbound
request. If no code path anywhere in the class ever removes entries —
no ``pop``/``del``/``clear``/``remove``/reassignment in a disconnect or
cleanup handler — the registry grows for the lifetime of the control
plane: client churn alone OOMs a multi-tenant hub that never restarts.

Flagged shape::

    class Hub:
        def __init__(self):
            self.jobs = {}
        def _on_register_job(self, conn, p):
            self.jobs[p["job_id"]] = make_entry(p)   # GL009
        # ...no method ever pops/dels/clears/reassigns self.jobs

Fix shape: prune in the disconnect/cleanup path (or bound the table)::

        def _handle_disconnect(self, conn):
            for job_id in self._jobs_of(conn):
                self.jobs.pop(job_id, None)

Scope is deliberately narrow to keep the signal clean:

- only instance attrs initialized EMPTY (``{}``/``dict()``/``[]``/
  ``list()``) in ``__init__`` — seeded tables are usually static maps;
- only growth sites written directly in *handler-shaped* methods
  (``_on_*`` message handlers and ``register_*`` registration
  endpoints) — request-path helpers have their own lifecycles;
- any trim anywhere in the class (``pop``/``popitem``/``popleft``/
  ``clear``/``remove``/``del x[k]``/slice-assign/reassignment outside
  ``__init__``) counts as the cleanup edge.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import FileContext, Finding, register, self_attr, walk_local

_GROW_CALLS = {"append", "extend", "insert", "appendleft", "setdefault"}
_TRIM_CALLS = {
    "pop", "popitem", "popleft", "remove", "clear", "discard",
}
_HANDLER_PREFIXES = ("_on_", "register_")


def _empty_container(value: Optional[ast.AST]) -> bool:
    if isinstance(value, ast.Dict) and not value.keys:
        return True
    if isinstance(value, ast.List) and not value.elts:
        return True
    if (
        isinstance(value, ast.Call)
        and isinstance(value.func, ast.Name)
        and value.func.id in ("dict", "list")
        and not value.args
        and not value.keywords
    ):
        return True
    return False


def _methods(cls: ast.ClassDef):
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield item


def _registry_attrs(cls: ast.ClassDef) -> Set[str]:
    attrs: Set[str] = set()
    for fn in _methods(cls):
        if fn.name != "__init__":
            continue
        for n in walk_local(fn):
            targets: List[ast.AST] = []
            if isinstance(n, ast.Assign) and _empty_container(n.value):
                targets = list(n.targets)
            elif isinstance(n, ast.AnnAssign) and _empty_container(n.value):
                targets = [n.target]
            for t in targets:
                a = self_attr(t)
                if a is not None:
                    attrs.add(a)
    return attrs


def _grow_sites(
    cls: ast.ClassDef, attrs: Set[str]
) -> Dict[str, List[Tuple[str, int]]]:
    """attr -> [(handler, line)] for growth written directly in a
    handler-shaped method (_on_* / register_*)."""
    grows: Dict[str, List[Tuple[str, int]]] = {}
    for fn in _methods(cls):
        if not fn.name.startswith(_HANDLER_PREFIXES):
            continue
        for n in walk_local(fn):
            # self.X[key] = ... (dict insert), possibly chained
            # (`m = self.X[key] = {...}`)
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        a = self_attr(t.value)
                        if a in attrs:
                            grows.setdefault(a, []).append(
                                (fn.name, n.lineno)
                            )
            # self.X.append(...) / self.X.setdefault(...)
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _GROW_CALLS
            ):
                a = self_attr(n.func.value)
                if a in attrs:
                    grows.setdefault(a, []).append((fn.name, n.lineno))
    return grows


def _trimmed_attrs(cls: ast.ClassDef, attrs: Set[str]) -> Set[str]:
    trimmed: Set[str] = set()
    for fn in _methods(cls):
        in_init = fn.name == "__init__"
        for n in walk_local(fn):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _TRIM_CALLS
            ):
                a = self_attr(n.func.value)
                if a in attrs:
                    trimmed.add(a)
            elif isinstance(n, ast.Delete):
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        a = self_attr(t.value)
                        if a in attrs:
                            trimmed.add(a)
            elif isinstance(n, ast.Assign) and not in_init:
                targets = [
                    e
                    for t in n.targets
                    for e in (
                        t.elts if isinstance(t, (ast.Tuple, ast.List))
                        else [t]
                    )
                ]
                for t in targets:
                    # reassignment resets; slice-assign can shrink
                    a = self_attr(t)
                    if a in attrs:
                        trimmed.add(a)
                    if isinstance(t, ast.Subscript):
                        a = self_attr(t.value)
                        if a in attrs and isinstance(t.slice, ast.Slice):
                            trimmed.add(a)
    return trimmed


@register("GL009", "unbounded-registry-growth")
def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        attrs = _registry_attrs(cls)
        if not attrs:
            continue
        grows = _grow_sites(cls, attrs)
        if not grows:
            continue
        trimmed = _trimmed_attrs(cls, attrs)
        for attr, sites in sorted(grows.items()):
            if attr in trimmed:
                continue
            meth, line = sites[0]
            out.append(
                Finding(
                    path=ctx.path,
                    line=line,
                    code="GL009",
                    message=(
                        f"registry `self.{attr}` is inserted into by "
                        f"handler `{cls.name}.{meth}` but no method of "
                        f"`{cls.name}` ever prunes it — a long-lived "
                        f"control plane leaks one entry per request; "
                        f"remove entries in the disconnect/cleanup path "
                        f"or bound the table"
                    ),
                    symbol=f"{cls.name}.{attr}",
                )
            )
    return out
