"""GL014 — cross-file lock-order cycles.

Deadlock by inverted acquisition order is invisible per-file: thread 1
holds ``A`` and wants ``B`` in one module, thread 2 holds ``B`` and
wants ``A`` in another, and each file looks locally sensible. This pass
builds the project-wide lock-acquisition graph and flags cycles.

Lock identity reuses GL001's modelling: ``with self._lock:`` names the
lock ``module.Class._lock`` (per-class, since each instance's lock is
distinct but acquisition *order* is a per-class property;
module-qualified so same-named classes in different modules hold
different locks), and a module-level ``with _REGISTRY_LOCK:`` names it
``module._REGISTRY_LOCK``. An edge
``A -> B`` exists when:

- a ``with B:`` is lexically nested inside a ``with A:``; or
- a method is called while holding ``A`` (``self.m()``, or ``obj.m()``
  with an inferable receiver class) and that method — transitively
  through the intra-class call graph — acquires ``B``.

Every cycle in the resulting digraph is a potential deadlock and is
reported once, anchored at one participating acquisition, with a
rotation-canonical symbol so the baseline fingerprint is stable no
matter which edge the walker happens to find first. Self-cycles
(``with self._lock:`` nested under itself) are reported too, unless
the lock is constructed as a ``threading.RLock`` (reentrant by
design).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, register_project, self_attr
from ..project import (
    ModuleInfo,
    ProjectSession,
    _call_name,
    is_lockish as _is_lockish,
)

_CODE = "GL014"


def _lock_id(mod: ModuleInfo, cls_name: Optional[str],
             expr: ast.AST) -> Optional[str]:
    a = self_attr(expr)
    if a is not None and _is_lockish(a):
        # module-qualified: two same-named classes in different modules
        # hold DIFFERENT locks (merging them fabricates phantom cycles)
        if cls_name:
            return f"{mod.basename}.{cls_name}.{a}"
        return f"{mod.basename}.{a}"
    if isinstance(expr, ast.Name) and _is_lockish(expr.id):
        return f"{mod.basename}.{expr.id}"
    return None


def _with_locks(mod: ModuleInfo, cls_name: Optional[str],
                node: ast.AST) -> List[str]:
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return []
    out = []
    for item in node.items:
        lid = _lock_id(mod, cls_name, item.context_expr)
        if lid is not None:
            out.append(lid)
    return out


class _Graph:
    def __init__(self) -> None:
        # A -> {B: (path, line, context)}
        self.edges: Dict[str, Dict[str, Tuple[str, int, str]]] = {}

    def add(self, a: str, b: str, site: Tuple[str, int, str]) -> None:
        self.edges.setdefault(a, {}).setdefault(b, site)
        self.edges.setdefault(b, {})


def _direct_locks(fn: ast.AST, mod: ModuleInfo,
                  cls_name: Optional[str]) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        out.update(_with_locks(mod, cls_name, n))
    return out


def _self_calls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            a = self_attr(n.func)
            if a is not None:
                out.add(a)
    return out


def _rlock_locks(session: ProjectSession) -> Set[str]:
    out: Set[str] = set()
    for mod in session.modules:
        for cls_name, cls in mod.classes.items():
            for n in ast.walk(cls):
                if isinstance(n, ast.Assign) and _call_name(
                        n.value) == "RLock":
                    for t in n.targets:
                        a = self_attr(t)
                        if a is not None:
                            out.add(f"{mod.basename}.{cls_name}.{a}")
        for n in mod.ctx.tree.body:
            if isinstance(n, ast.Assign) and _call_name(n.value) == "RLock":
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out.add(f"{mod.basename}.{t.id}")
    return out


def _transitive_locks(session: ProjectSession) -> Dict[Tuple[str, str],
                                                       Set[str]]:
    """(class, method) -> every lock the method may acquire, following
    intra-class calls to a fixpoint."""
    direct: Dict[Tuple[int, str, str], Set[str]] = {}
    calls: Dict[Tuple[int, str, str], Set[str]] = {}
    for mod in session.modules:
        for cls_name, cls in mod.classes.items():
            for mname, fn in mod.methods(cls).items():
                key = (id(mod), cls_name, mname)
                direct[key] = _direct_locks(fn, mod, cls_name)
                calls[key] = _self_calls(fn)
    trans = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for (mid, cls_name, mname), callees in calls.items():
            cur = trans[(mid, cls_name, mname)]
            for c in callees:
                sub = trans.get((mid, cls_name, c))
                if sub and not sub <= cur:
                    cur |= sub
                    changed = True
    return trans


def _collect_edges(session: ProjectSession, graph: _Graph,
                   trans: Dict[Tuple[str, str], Set[str]]) -> None:
    for mod in session.modules:
        scopes: List[Tuple[Optional[str], ast.AST]] = [
            (None, fnode) for fnode in mod.functions.values()
        ]
        for cls_name, cls in mod.classes.items():
            for fn in mod.methods(cls).values():
                scopes.append((cls_name, fn))
        for cls_name, fn in scopes:
            ctx_name = (f"{cls_name}.{fn.name}" if cls_name else fn.name)

            def visit(node: ast.AST, held: List[str]) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue
                    locks = _with_locks(mod, cls_name, child)
                    if locks:
                        for a in held:
                            for b in locks:
                                graph.add(a, b, (mod.path, child.lineno,
                                                 ctx_name))
                        visit(child, held + locks)
                        continue
                    if held and isinstance(child, ast.Call):
                        callee_locks: Set[str] = set()
                        a = self_attr(child.func)
                        if a is not None and cls_name is not None:
                            callee_locks = trans.get(
                                (id(mod), cls_name, a), set())
                        if callee_locks:
                            for ha in held:
                                for b in callee_locks:
                                    if b == ha:
                                        continue  # re-entry is GL001's beat
                                    graph.add(ha, b,
                                              (mod.path, child.lineno,
                                               ctx_name))
                    visit(child, held)

            visit(fn, [])


def _find_cycles(graph: _Graph) -> List[List[str]]:
    """Elementary cycles, deduped by rotation-canonical form. DFS with
    a bound that is far above any plausible lock graph here."""
    cycles: Set[Tuple[str, ...]] = set()
    edges = graph.edges

    def canon(path: List[str]) -> Tuple[str, ...]:
        i = path.index(min(path))
        return tuple(path[i:] + path[:i])

    for start in sorted(edges):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, ())):
                if nxt == start:
                    cycles.add(canon(path))
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))
    return [list(c) for c in sorted(cycles)]


@register_project(_CODE, "lock-order")
def check(session: ProjectSession) -> List[Finding]:
    graph = _Graph()
    trans = _transitive_locks(session)
    _collect_edges(session, graph, trans)
    reentrant = _rlock_locks(session)
    out: List[Finding] = []
    for cycle in _find_cycles(graph):
        if len(cycle) == 1 and cycle[0] in reentrant:
            continue
        ring = cycle + [cycle[0]]
        path, line, ctx = graph.edges[cycle[0]][ring[1]]
        order = " -> ".join(ring)
        if len(cycle) == 1:
            msg = (
                f"lock {cycle[0]} is acquired while already held "
                f"(in {ctx}) and is not an RLock — guaranteed "
                f"self-deadlock on this path"
            )
        else:
            msg = (
                f"lock-order cycle {order}: two threads taking these "
                f"locks in opposite orders can deadlock; pick one "
                f"global order (or collapse to one lock)"
            )
        out.append(Finding(
            path=path, line=line, code=_CODE, message=msg,
            symbol="cycle:" + "->".join(ring),
        ))
    return out
