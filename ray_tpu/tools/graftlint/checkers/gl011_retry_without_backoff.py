"""GL011 — fixed-interval retry/retransmit loop (no backoff).

The client bug class: ``CoreClient.request`` parked on a reply future
and re-sent the request every fixed ~2s, forever. Fixed-cadence
retransmit turns every hub stall into a synchronized storm — all
clients resend on the same beat, the recovering peer takes the full
herd at once, stalls again, and the system ratchets into lockstep
congestion (the thundering-herd failure the reference avoids with
exponential backoff in ``rpc/retryable_grpc_client.h``).

The checker flags a ``while`` loop in runtime-core code
(any ``_private/`` package, plus ``ray_tpu/serve/`` — the serve plane's
ejection re-probe and transparent handle-retry loops resend on exactly
this shape) that

  1. parks on a *wait-like* call (``.wait(...)``, a ``*wait`` helper
     such as ``concurrent.futures.wait``, or ``time.sleep``) whose
     timeout/duration argument never grows, AND
  2. re-sends something (``send`` / ``send_async`` / ``send_bytes`` /
     ``request``) in the same loop, AND
  3. contains no backoff term for the delay: no ``delay *= k`` /
     ``delay += k`` aug-assign and no re-assignment of the delay
     variable whose value refers to the variable itself through a
     multiplicative/additive expression (``delay = min(cap, delay*2)``
     counts; ``remaining = min(remaining, deadline - now)`` — a pure
     deadline clamp — does not).

Periodic *senders* (heartbeat loops pacing on ``conn.poll``; flush
loops with no resend call) are not wait-like + resend pairs and stay
clean. Fix shape: capped exponential backoff with jitter —
``delay = min(CAP, delay * 2)`` plus a randomized wait.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from ..core import FileContext, Finding, qualname_map, register, walk_local

# attribute/function spellings that park the loop for a bounded time
_WAIT_ATTRS = {"wait", "sleep"}
# attribute spellings that (re-)transmit on the wire; "remote" covers
# the serve plane (handle retries / health re-probes dispatch through
# actor_method.remote(...))
_RESEND_ATTRS = {"send", "send_async", "send_bytes", "request", "remote"}


def _is_wait_call(node: ast.Call, ctx: FileContext) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in _WAIT_ATTRS
    if isinstance(fn, ast.Name):
        resolved = ctx.resolve(fn.id) or fn.id
        return resolved == "time.sleep" or fn.id.endswith("wait")
    return False


def _is_resend_call(node: ast.Call) -> bool:
    fn = node.func
    return isinstance(fn, ast.Attribute) and fn.attr in _RESEND_ATTRS


def _timeout_expr(node: ast.Call) -> Optional[ast.AST]:
    """The duration the wait parks for: a `timeout=` kwarg, else the
    last positional arg (Event.wait(t) / time.sleep(t)); None for a
    bare wait() (wakes only by signal — not a cadence)."""
    for kw in node.keywords:
        if kw.arg == "timeout":
            return kw.value
    if node.args:
        return node.args[-1]
    return None


def _delay_names(expr: ast.AST) -> Set[str]:
    """Local variable names the wait duration is computed from
    (`self`/`cls` excluded: every method call mentions them, and a
    receiver is not a delay value — keeping them would let ANY
    `x = self.f(...)` masquerade as a backoff term)."""
    return {
        n.id for n in ast.walk(expr)
        if isinstance(n, ast.Name) and n.id not in ("self", "cls")
    }


_GROWTH_OPS = (ast.Mult, ast.Pow, ast.Add)


def _assign_targets(node: ast.Assign) -> Set[str]:
    out: Set[str] = set()
    for t in node.targets:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            out |= {e.id for e in t.elts if isinstance(e, ast.Name)}
    return out


def _expand_delay_names(loop: ast.While, names: Set[str]) -> Set[str]:
    """Backward dataflow closure: every local name the wait duration is
    derived from inside the loop (`remaining = resync * jitter` puts
    `resync` in the closure, so growth on it counts as backoff)."""
    out = set(names)
    changed = True
    while changed:
        changed = False
        for node in walk_local(loop):
            if not isinstance(node, ast.Assign):
                continue
            if not (_assign_targets(node) & out):
                continue
            new = _delay_names(node.value) - out
            if new:
                out |= new
                changed = True
    return out


def _has_growth(loop: ast.While, names: Set[str]) -> bool:
    """Does any statement in the loop grow a delay-chain variable —
    reassign it *in terms of itself* through a multiplicative/additive
    expression or a helper call (aug-assign counts too)? A pure clamp
    (`remaining = min(remaining, deadline - now)`) is not growth."""
    for node in walk_local(loop):
        if isinstance(node, ast.AugAssign):
            if (
                isinstance(node.target, ast.Name)
                and node.target.id in names
                and isinstance(node.op, _GROWTH_OPS)
            ):
                return True
        elif isinstance(node, ast.Assign):
            rhs_names = _delay_names(node.value)
            targets = _assign_targets(node)
            if not (targets & names):
                continue
            # growth-helper call: a delay-chain variable rebound from a
            # call fed by the chain (`wait, delay = self._retry_delay(delay)`,
            # or the conditional shape `wait, nxt = self._retry_delay(cur)`
            # + `cur = nxt` — nxt/cur are both in the closure). Bare
            # min()/max() are clamps, not growth — the pre-fix GET
            # loop's deadline clamp must still flag.
            if isinstance(node.value, ast.Call) and not (
                isinstance(node.value.func, ast.Name)
                and node.value.func.id in ("min", "max")
            ):
                if rhs_names & names:
                    return True
                continue
            # min()/max() falls through: `min(cap, delay * 2)` is
            # growth by its BinOp; a pure deadline clamp has none
            # self-referential: some delay-chain variable is rebound
            # from an expression that mentions it
            if not (targets & names & rhs_names):
                continue
            if any(
                isinstance(n, ast.BinOp) and isinstance(n.op, _GROWTH_OPS)
                for n in ast.walk(node.value)
            ):
                return True
    return False


@register("GL011", "retry-without-backoff")
def check(ctx: FileContext) -> List[Finding]:
    norm = "/" + ctx.path.replace(os.sep, "/")
    if "/_private/" not in norm and "ray_tpu/serve/" not in norm:
        return []
    out: List[Finding] = []
    quals = qualname_map(ctx.tree)
    fns = [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in fns:
        for loop in walk_local(fn):
            if not isinstance(loop, ast.While):
                continue
            waits = [
                n for n in walk_local(loop)
                if isinstance(n, ast.Call) and _is_wait_call(n, ctx)
            ]
            resends = [
                n for n in walk_local(loop)
                if isinstance(n, ast.Call) and _is_resend_call(n)
            ]
            if not waits or not resends:
                continue
            names: Set[str] = set()
            constant_only = False
            for w in waits:
                expr = _timeout_expr(w)
                if expr is None:
                    continue
                n = _delay_names(expr)
                if n:
                    names |= n
                else:
                    constant_only = True  # .wait(2.0): literal cadence
            if not names and not constant_only:
                continue  # bare wait(): signal-driven, no cadence
            if names and _has_growth(
                loop, _expand_delay_names(loop, names)
            ):
                continue
            out.append(
                Finding(
                    path=ctx.path,
                    line=loop.lineno,
                    code="GL011",
                    message=(
                        "fixed-interval retransmit loop: the wait "
                        "duration never grows between resends — a hub "
                        "stall makes every client resend on the same "
                        "beat. Use capped exponential backoff with "
                        "jitter (delay = min(CAP, delay * 2))"
                    ),
                    symbol=quals.get(id(fn), fn.name),
                )
            )
    return out
