"""CLI: ``python -m ray_tpu.tools.graftlint [paths] [options]``.

Exit status: 0 when every finding is suppressed or baselined, 1 when
new findings exist, 2 on usage errors. Findings print one per line as
``path:line GLxxx message`` (or as one JSON object with
``--format json``, or as a SARIF 2.1.0 log with ``--format sarif`` for
CI annotation uploads).

``--changed-only`` reports per-file findings only in files git
considers changed (worktree/index vs HEAD, plus untracked) — the fast
pre-commit mode. The whole tree is still ANALYZED, and whole-program
findings (GL012–GL017) always report regardless of where they anchor:
deleting a handler must surface the sent-but-unhandled finding even
though it anchors at the untouched send site. Both structured formats
compose with it.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

from .core import (
    DEFAULT_BASELINE_PATH,
    all_checkers,
    all_project_checkers,
    check_paths,
    load_baseline,
    write_baseline,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.graftlint",
        description=(
            "AST-based concurrency & distributed-runtime invariant "
            "checker for this repo: per-file rules GL001-GL011 plus "
            "whole-program passes GL012-GL017 (see the package README)."
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=["ray_tpu"],
        help="files or directories to check (default: ray_tpu)",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE_PATH, metavar="FILE",
        help="baseline JSON of accepted findings "
             "(default: the packaged baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline; report every finding",
    )
    parser.add_argument(
        "--write-baseline", metavar="FILE",
        help="write all current findings to FILE as the new baseline "
             "and exit 0",
    )
    parser.add_argument(
        "--select", metavar="CODES",
        help="comma-separated rule codes to run (e.g. GL001,GL005)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the summary line; print findings only",
    )
    parser.add_argument(
        "--format", choices=["text", "json", "sarif"], default="text",
        help="output format (json: one object with findings + counts; "
             "sarif: a SARIF 2.1.0 log for CI annotation uploads)",
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="report per-file findings only in git-changed files "
             "(whole-program findings always report; the whole tree "
             "is always analyzed)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, name, _fn in sorted(all_checkers() + all_project_checkers()):
            print(f"{code}  {name}")
        return 0

    for p in args.paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2

    codes = None
    if args.select:
        codes = {c.strip().upper() for c in args.select.split(",") if c.strip()}
        known = {
            code
            for code, _name, _fn in all_checkers() + all_project_checkers()
        }
        unknown = sorted(codes - known)
        if unknown:
            # a typo'd code must not silently run zero checkers and
            # green-light the tree
            print(
                f"graftlint: unknown rule code(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(known))})",
                file=sys.stderr,
            )
            return 2

    report_only: Optional[Set[str]] = None
    if args.changed_only:
        if args.write_baseline:
            # a diff-scoped run drops every out-of-scope finding, so
            # the written baseline would silently lose all accepted
            # fingerprints outside the diff
            print(
                "graftlint: --write-baseline needs the full finding "
                "set; drop --changed-only",
                file=sys.stderr,
            )
            return 2
        report_only = _git_changed_files(args.paths)
        if report_only is None:
            print(
                "graftlint: --changed-only needs the analyzed paths "
                "inside a git checkout (git rev-parse failed)",
                file=sys.stderr,
            )
            return 2

    baseline = (
        set() if (args.no_baseline or args.write_baseline)
        else load_baseline(args.baseline)
    )
    new, old = check_paths(
        args.paths, baseline=baseline, codes=codes,
        report_only=report_only,
    )

    if args.write_baseline:
        write_baseline(args.write_baseline, new + old)
        if not args.quiet:
            print(
                f"graftlint: wrote {len(new) + len(old)} finding(s) to "
                f"{args.write_baseline}"
            )
        return 0

    if args.format == "json":
        print(json.dumps(
            {
                "findings": [dataclasses.asdict(f) for f in new],
                "baselined": len(old),
                "changed_only": bool(args.changed_only),
            },
            indent=2, sort_keys=True,
        ))
        return 1 if new else 0

    if args.format == "sarif":
        print(json.dumps(_sarif_log(new), indent=2, sort_keys=True))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if not args.quiet:
        suffix = f" ({len(old)} baselined)" if old else ""
        print(
            f"graftlint: {len(new)} finding(s){suffix}",
            file=sys.stderr,
        )
    return 1 if new else 0


def _sarif_log(findings) -> dict:
    """SARIF 2.1.0: the interchange format CI systems (GitHub code
    scanning, pre-commit annotators) ingest directly. One run, one
    result per finding; ``partialFingerprints`` carries the same
    (path, code, symbol) identity the baseline uses, so an uploader
    dedupes findings across pushes exactly as the baseline would."""
    rules_seen = {}
    results = []
    for f in findings:
        rules_seen.setdefault(f.code, {
            "id": f.code,
            "defaultConfiguration": {"level": "error"},
        })
        results.append({
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/"),
                    },
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
            "partialFingerprints": {
                "graftlint/v1": f"{f.path}:{f.code}:{f.symbol}",
            },
        })
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "graftlint",
                    "rules": [
                        rules_seen[c] for c in sorted(rules_seen)
                    ],
                },
            },
            "results": results,
        }],
    }


def _git_changed_files(paths: List[str]) -> Optional[Set[str]]:
    """Absolute paths of files changed vs HEAD (worktree + index) plus
    untracked files, for the checkout CONTAINING the analyzed paths —
    not the process CWD, which may sit in an unrelated repo (running
    graftlint on an absolute path from $HOME must not diff the
    operator's dotfiles). None when no git checkout is found there."""
    anchor = os.path.abspath(paths[0]) if paths else os.getcwd()
    if not os.path.isdir(anchor):
        anchor = os.path.dirname(anchor) or "."

    def run(*cmd: str) -> Optional[List[str]]:
        try:
            r = subprocess.run(
                list(cmd), capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        return [ln for ln in r.stdout.splitlines() if ln.strip()]

    top = run("git", "-C", anchor, "rev-parse", "--show-toplevel")
    if not top:
        return None
    root = top[0]
    names: Set[str] = set()
    # vs HEAD covers both staged and unstaged edits; a repo with no
    # commit yet has no HEAD — fall back to the index diff
    diff = run("git", "-C", root, "diff", "--name-only", "HEAD", "--")
    if diff is None:
        diff = run("git", "-C", root, "diff", "--name-only", "--") or []
    names.update(diff)
    names.update(
        run("git", "-C", root, "ls-files", "--others",
            "--exclude-standard") or []
    )
    return {os.path.join(root, n) for n in names}


if __name__ == "__main__":
    sys.exit(main())
