"""CLI: ``python -m ray_tpu.tools.graftlint [paths] [options]``.

Exit status: 0 when every finding is suppressed or baselined, 1 when
new findings exist, 2 on usage errors. Findings print one per line as
``path:line GLxxx message``.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .core import (
    DEFAULT_BASELINE_PATH,
    all_checkers,
    check_paths,
    load_baseline,
    write_baseline,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.graftlint",
        description=(
            "AST-based concurrency & distributed-runtime invariant "
            "checker for this repo (rules GL001-GL006; see the package "
            "README)."
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=["ray_tpu"],
        help="files or directories to check (default: ray_tpu)",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE_PATH, metavar="FILE",
        help="baseline JSON of accepted findings "
             "(default: the packaged baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline; report every finding",
    )
    parser.add_argument(
        "--write-baseline", metavar="FILE",
        help="write all current findings to FILE as the new baseline "
             "and exit 0",
    )
    parser.add_argument(
        "--select", metavar="CODES",
        help="comma-separated rule codes to run (e.g. GL001,GL005)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the summary line; print findings only",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, name, _fn in sorted(all_checkers()):
            print(f"{code}  {name}")
        return 0

    for p in args.paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2

    codes = None
    if args.select:
        codes = {c.strip().upper() for c in args.select.split(",") if c.strip()}
        known = {code for code, _name, _fn in all_checkers()}
        unknown = sorted(codes - known)
        if unknown:
            # a typo'd code must not silently run zero checkers and
            # green-light the tree
            print(
                f"graftlint: unknown rule code(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(known))})",
                file=sys.stderr,
            )
            return 2

    baseline = (
        set() if (args.no_baseline or args.write_baseline)
        else load_baseline(args.baseline)
    )
    new, old = check_paths(args.paths, baseline=baseline, codes=codes)

    if args.write_baseline:
        write_baseline(args.write_baseline, new + old)
        if not args.quiet:
            print(
                f"graftlint: wrote {len(new) + len(old)} finding(s) to "
                f"{args.write_baseline}"
            )
        return 0

    for f in new:
        print(f.render())
    if not args.quiet:
        suffix = f" ({len(old)} baselined)" if old else ""
        print(
            f"graftlint: {len(new)} finding(s){suffix}",
            file=sys.stderr,
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
