"""Project-level analysis session for graftlint's whole-program passes.

The per-file rules (GL001–GL011) see one ``FileContext`` at a time; the
whole-program passes (GL012–GL017) need the whole tree at once: the
wire contract lives in ``protocol.py`` but is *exercised* by send sites
in five different processes, thread ownership crosses the
``hub.py``/``hub_shards.py`` module boundary, a lock cycle is only
visible when both acquisition orders are in the graph, the sync helper
that stalls a coroutine lives modules away from the ``async def`` that
calls it, and a selector registered in one method is unregistered in
another.

``ProjectSession`` wraps one shared parse of the tree (every
``FileContext`` comes from ``core.parse_cached``, so nothing here costs
a second ``ast.parse``) and exposes the derived models the passes
consume:

- a **module/class index** with import-alias resolution that understands
  the repo's relative imports (``from . import protocol as P``);
- the **protocol model** (:meth:`ProjectSession.protocol`): message
  constants, every recognized send site (``_send``/``send``/
  ``send_async``/``request``/``_traced_send``/``_reply``, raw
  ``dumps_frame((msg, payload))`` framing, and ``(msg, payload)``
  tuples appended to a send buffer (batch coalescing: the append IS
  the send; for ``send_async`` itself coalescing happens *below* the
  call, so the call site is the send), and
  every dispatch table in its three spellings: dict literals
  (``self._inbound_handlers = {...}``), convention tables
  (``{name[len("_on_"):]: getattr(self, name) for name in
  dir(type(self)) if name.startswith("_on_")}``), and
  ``if/elif msg_type == P.X`` chains; plus module-level routing sets
  (``SCHEDULER_MSGS``/``OBJECT_MSGS`` feeding ``SERVICE_OF``) for the
  sharded topology;
- the **thread model** (:meth:`ProjectSession.threads`): per-class
  ownership domains seeded from entry points (``threading.Thread``
  construction targets, Thread-subclass/reactor ``run``, dispatch-table
  handlers, ``_add_timer`` callbacks, ``_read_loop``) and propagated
  through the intra-class call graph, plus a light attribute-type
  inference (``self.x = Cls(...)``, ``[Cls(...) for ...]``,
  annotations) so a pass can tell that ``s`` in
  ``for s in self._shards:`` is a ``ReactorShard``;
- the **flow model** (:meth:`ProjectSession.flow`): the project call
  graph keyed ``module.Class.method``, with GL003's blocking tables as
  roots (shared recognition — the per-file and whole-program notions
  of "a blocking op" cannot diverge), locks held by thread-domain
  methods around blocking work, trace-contextvar reads, and
  executor/thread closure dispatches (GL015);
- the **resource model** (:meth:`ProjectSession.resources`): per-class
  acquire/release pairing sites — selector names (constructor-typed
  attrs/locals plus aliases), register/unregister/close sites, timer
  heaps and their teardown clears, and handle registries with their
  drop paths (GL016).

Everything is lazy and cached per session; a session is cheap to build
(no parsing — the trees come from the core parse cache) and throwaway
by design.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import FileContext, dotted_name, qualname_map, self_attr

__all__ = [
    "ProjectSession",
    "ModuleInfo",
    "SendSite",
    "Handler",
    "DispatchTable",
    "RoutingSet",
    "ProtocolModel",
    "ClassThreads",
    "ThreadModel",
    "FlowFunction",
    "FlowModel",
    "ResourceClass",
    "ResourceModel",
    "session_for",
]

# method names that put a (msg_type, payload) message on a wire/queue.
# _reply is special-cased below (implicit REPLY + keyword payload).
SEND_APIS = frozenset({"send", "send_async", "request", "_send",
                       "_traced_send"})

# wire-framing / in-process sentinels, never part of the message model
FRAMING_TYPES = frozenset({"batch"})

# variable names that identify an if/elif chain as message dispatch
# (``if kind == P.VAL_SHM`` style value comparisons must NOT register
# as handler tables, so the chain form is gated on the variable name)
MSG_VAR_NAMES = frozenset({"msg_type", "mt", "msg", "message_type"})

_REACTOR_CLASS = re.compile(r"(Shard|Reactor)")


def _is_internal(msg: str) -> bool:
    return msg.startswith("__") and msg.endswith("__")


# --------------------------------------------------------------------- module


@dataclass
class ModuleInfo:
    ctx: FileContext
    basename: str                       # "hub" for .../hub.py
    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    functions: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    qualnames: Dict[int, str] = field(default_factory=dict)
    # local alias -> session-module basename ("P" -> "protocol")
    module_aliases: Dict[str, str] = field(default_factory=dict)

    @property
    def path(self) -> str:
        return self.ctx.path

    def methods(self, cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
        return {
            item.name: item
            for item in cls.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }


# ------------------------------------------------------------- protocol model


@dataclass
class SendSite:
    module: ModuleInfo
    line: int
    msg: str                            # resolved message-type value
    symbol: str                         # enclosing qualname for fingerprints
    # payload keys when the payload is a fully-tracked literal dict
    # (literal at the call, or a local assigned a literal and augmented
    # only by `var["k"] = ...` before the send); None = opaque
    keys: Optional[FrozenSet[str]]
    via: str                            # the send API spelling used
    raw_string: bool                    # msg given as a bare string literal
    # vector payloads (bulk frames like SUBMIT_TASKS): payload key ->
    # keys of the homogeneous dict items under it, when the value is a
    # tracked list-of-dict-literals ([{...}, ...] or [{...} for ...]).
    # Only keys EVERY item carries are recorded, so handler-side
    # per-item required reads can be checked against them.
    item_keys: Dict[str, FrozenSet[str]] = field(default_factory=dict)


@dataclass
class Handler:
    module: ModuleInfo
    line: int
    msg: str
    symbol: str
    required_keys: FrozenSet[str]       # plain-subscript reads, unconditional
    read_keys: FrozenSet[str]           # every key read in any way
    opaque: bool                        # payload escapes / is iterated: the
                                        # read set is a lower bound only
    raw_string: bool
    # vector payloads: payload key -> item keys the handler reads on
    # EVERY element of ``for t in payload[k]:`` loops (plain subscript,
    # unconditional within the loop body). item_read is every item key
    # read in any way (.get included).
    item_required: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    item_read: Dict[str, FrozenSet[str]] = field(default_factory=dict)


@dataclass
class DispatchTable:
    module: ModuleInfo
    line: int
    kind: str                           # "dict" | "prefix" | "elif"
    owner: str                          # class or function qualname
    msgs: FrozenSet[str]


@dataclass
class RoutingSet:
    module: ModuleInfo
    line: int
    name: str
    msgs: FrozenSet[str]
    sharded: bool                       # lives in a reactor-shard module


@dataclass
class ProtocolModel:
    constants: Dict[str, str]           # NAME -> value (protocol module)
    constant_values: Set[str]
    protocol_module: Optional[ModuleInfo]
    sends: List[SendSite]
    handlers: List[Handler]
    tables: List[DispatchTable]
    routing_sets: List[RoutingSet]
    # message values consumed by ad-hoc comparison (``mt != "obj_data"``)
    # — the request/response object plane reads replies inline rather
    # than through a dispatch table, and a comparison is evidence the
    # type is expected by a receiver
    compared: Set[str] = field(default_factory=set)

    def sends_of(self, msg: str) -> List[SendSite]:
        return [s for s in self.sends if s.msg == msg]

    def handlers_of(self, msg: str) -> List[Handler]:
        return [h for h in self.handlers if h.msg == msg]


# --------------------------------------------------------------- thread model


@dataclass
class ClassThreads:
    module: ModuleInfo
    cls: ast.ClassDef
    qual: str                           # "hub_shards.ReactorShard"
    # method name -> set of domain labels it may run under
    domains: Dict[str, Set[str]] = field(default_factory=dict)
    # attr name -> constructed/annotated class name, when inferable
    attr_types: Dict[str, str] = field(default_factory=dict)
    # attrs holding recognized cross-thread channels (rings, queues,
    # events, locks): mutating them IS the sanctioned crossing
    channel_attrs: Set[str] = field(default_factory=set)

    def all_domains(self) -> Set[str]:
        out: Set[str] = set()
        for d in self.domains.values():
            out |= d
        return out


@dataclass
class ThreadModel:
    # keyed by qualified name ("hub_shards.ReactorShard") so two
    # same-named classes in different modules are BOTH analyzed
    classes: Dict[str, ClassThreads]
    # bare name -> every definition, for type-inference lookups
    by_name: Dict[str, List[ClassThreads]] = field(default_factory=dict)

    def resolve(self, cls_name: str) -> Optional["ClassThreads"]:
        """First definition carrying that bare name (the same
        first-hit rule as ``ProjectSession.resolve_class``, which the
        type inference producing these names uses)."""
        hits = self.by_name.get(cls_name)
        return hits[0] if hits else None

    def domains_of(self, cls_name: str, method: str) -> Set[str]:
        info = self.resolve(cls_name)
        if info is None:
            return set()
        return info.domains.get(method, set())


# ----------------------------------------------------------------- flow model


@dataclass
class FlowFunction:
    """One function in the project call/blocking graph (GL015)."""

    module: ModuleInfo
    node: ast.AST                       # FunctionDef / AsyncFunctionDef
    key: str                            # "hub.Hub._run" / "client.connect"
    qual: str                           # module-local qualname
    is_async: bool
    cls_name: Optional[str] = None
    # direct known-blocking ops in this function's own body:
    # (line, human description)
    blocking: List[Tuple[int, str]] = field(default_factory=list)
    # resolved direct calls: (line, callee key, under-an-await,
    # bare-statement)
    calls: List[Tuple[int, str, bool, bool]] = field(default_factory=list)
    # lockish self-attrs acquired via ``with self.X:``
    # ("module.Class.X")
    locks: Set[str] = field(default_factory=set)
    # the function reads the ambient trace contextvar (directly or via
    # begin_trace, which samples against the current context)
    reads_trace_ctx: bool = False
    # run_in_executor/Thread(target=) dispatches of a local closure
    # that does NOT re-push the trace context and is not under an
    # ``if <name> is None:`` no-trace guard: (line, closure name)
    ctx_unsafe_dispatches: List[Tuple[int, str]] = field(
        default_factory=list)


@dataclass
class FlowModel:
    """Project-wide call graph + blocking roots (GL015).

    ``functions`` is keyed ``module.Class.method`` / ``module.fn``.
    ``slow_thread_locks`` maps a lock id ("module.Class.attr") to a
    description of the thread-domain holder that performs a blocking op
    while holding it — waiting on such a lock from the event loop can
    stall for the holder's full blocking window, so acquiring one
    counts as a blocking root for the transitive analysis.
    """

    functions: Dict[str, FlowFunction]
    slow_thread_locks: Dict[str, str] = field(default_factory=dict)

    def blocking_chain(self, key: str) -> Optional[List[str]]:
        """["module.fn", ..., "<op description>"] for the first found
        path from ``key`` into a blocking root; None when ``key``
        cannot block. Memoized; cycles are cut (a cycle with no
        blocking op on it never blocks)."""
        memo: Dict[str, Optional[List[str]]] = self.__dict__.setdefault(
            "_chain_memo", {})

        def walk(k: str, visiting: Set[str]) -> Optional[List[str]]:
            if k in memo:
                return memo[k]
            fn = self.functions.get(k)
            if fn is None or k in visiting:
                return None
            visiting.add(k)
            result: Optional[List[str]] = None
            if fn.blocking:
                result = [k, fn.blocking[0][1]]
            else:
                for lock in sorted(fn.locks):
                    holder = self.slow_thread_locks.get(lock)
                    if holder is not None:
                        result = [k, f"`with {lock}:` — {holder}"]
                        break
            if result is None:
                for _line, callee, awaited, _stmt in fn.calls:
                    if awaited:
                        continue
                    sub_fn = self.functions.get(callee)
                    if sub_fn is None or sub_fn.is_async:
                        continue
                    sub = walk(callee, visiting)
                    if sub is not None:
                        result = [k] + sub
                        break
            visiting.discard(k)
            memo[k] = result
            return result

        return walk(key, set())


# ------------------------------------------------------------- resource model


# constructor/factory trailing names that hand back an owned OS-level
# handle (or a record that must reach an emitter). The value is the
# human-readable resource kind.
ACQUIRE_CTORS = {
    "mmap": "mmap segment",
    "MappedSegment": "mmap segment",
    "from_fd": "mmap segment",
    "DefaultSelector": "selector",
    "socket": "socket",
    "create_connection": "socket",
    "make_runtime_record": "span record",
}

# method names that release an owned handle
RELEASE_METHODS = frozenset({"close", "unmap", "shutdown", "release",
                             "cancel", "detach", "terminate"})


@dataclass
class ResourceClass:
    """Per-class resource-lifecycle sites (GL016)."""

    module: ModuleInfo
    cls_name: str
    qual: str                           # "hub.Hub"
    # attrs/locals typed as selectors (assigned from DefaultSelector(),
    # or aliased from such an attr)
    selector_names: Set[str] = field(default_factory=set)
    register_sites: List[int] = field(default_factory=list)
    unregister_sites: List[int] = field(default_factory=list)
    selector_close_sites: List[int] = field(default_factory=list)
    # one-shot timer heaps: attr -> heappush lines
    timer_attrs: Dict[str, List[int]] = field(default_factory=dict)
    # attr -> clear/teardown-reassign lines
    timer_clears: Dict[str, List[int]] = field(default_factory=dict)
    # handle registries: attr -> store lines (``self.X[k] = handle``
    # where the handle was acquired locally — ownership transfer)
    registry_attrs: Dict[str, List[int]] = field(default_factory=dict)
    # attr -> removal lines (pop / del / clear)
    registry_drops: Dict[str, List[int]] = field(default_factory=dict)


@dataclass
class ResourceModel:
    classes: Dict[str, ResourceClass]   # keyed by qual

    def resolve(self, cls_name: str) -> Optional["ResourceClass"]:
        for info in self.classes.values():
            if info.cls_name == cls_name:
                return info
        return None


# recognized channel constructors: pushing/popping one of these crosses
# threads by design, so the attribute itself is exempt from ownership
# conflicts (the GL013 "ring/queue crossing")
CHANNEL_CTORS = frozenset({
    "deque", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "Event", "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
    "Lock", "RLock", "ShardRing",
})
_CHANNEL_NAME_HINTS = ("ring", "queue", "lock", "cond", "evt", "event",
                       "sem", "mutex", "_buf")

# name hints identifying a lock-like object. ONE definition shared by
# GL013 (exempts lock-ish attrs from ownership conflicts) and GL014
# (identifies acquisitions) — the two rules' notions of "a lock" must
# never diverge, or an attr one pass exempts stops being modelled by
# the other.
LOCK_NAME_HINTS = ("lock", "mutex", "cond", "cv")


def is_lockish(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in LOCK_NAME_HINTS)


def _channel_name(attr: str) -> bool:
    low = attr.lower()
    return any(h in low for h in _CHANNEL_NAME_HINTS)


# ------------------------------------------------------------------- helpers


def _call_name(node: ast.AST) -> Optional[str]:
    """Trailing name of the called thing: ``threading.Thread`` ->
    "Thread", ``ShardRing(...)`` -> "ShardRing"."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _functions_in(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class _FnIndex:
    """Per-module map node-id -> enclosing (class name, function name)."""

    def __init__(self, mod: ModuleInfo):
        self.owner: Dict[int, Tuple[Optional[str], Optional[str]]] = {}

        def visit(node, cls, fn):
            for child in ast.iter_child_nodes(node):
                c, f = cls, fn
                if isinstance(child, ast.ClassDef):
                    c, f = child.name, None
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    f = child.name
                self.owner[id(child)] = (c, f)
                visit(child, c, f)

        visit(mod.ctx.tree, None, None)


# -------------------------------------------------------------------- session


class ProjectSession:
    """One shared view of a set of parsed files (see module docstring)."""

    def __init__(self, contexts: Sequence[FileContext]):
        self.modules: List[ModuleInfo] = []
        self.by_basename: Dict[str, List[ModuleInfo]] = {}
        self.class_index: Dict[str, List[Tuple[ModuleInfo, ast.ClassDef]]] = {}
        for ctx in contexts:
            base = os.path.splitext(os.path.basename(ctx.path))[0]
            mod = ModuleInfo(ctx=ctx, basename=base)
            mod.qualnames = qualname_map(ctx.tree)
            for node in ctx.tree.body:
                if isinstance(node, ast.ClassDef):
                    mod.classes[node.name] = node
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    mod.functions[node.name] = node
            self.modules.append(mod)
            self.by_basename.setdefault(base, []).append(mod)
        for mod in self.modules:
            mod.module_aliases = self._module_aliases(mod)
            for name, cls in mod.classes.items():
                self.class_index.setdefault(name, []).append((mod, cls))
        self._protocol: Optional[ProtocolModel] = None
        self._threads: Optional[ThreadModel] = None
        self._flow: Optional[FlowModel] = None
        self._resources: Optional[ResourceModel] = None

    # ------------------------------------------------------------ module refs
    def _module_aliases(self, mod: ModuleInfo) -> Dict[str, str]:
        """Aliases bound to *session* modules, through absolute AND
        relative imports: ``from . import protocol as P`` -> {"P":
        "protocol"} when a ``protocol`` module is in the session."""
        out: Dict[str, str] = {}
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    tail = a.name.split(".")[-1]
                    if tail in self.by_basename:
                        out[a.asname or tail] = tail
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "*":
                        continue
                    if a.name in self.by_basename:
                        out[a.asname or a.name] = a.name
        return out

    def resolve_class(
        self, name: Optional[str]
    ) -> Optional[Tuple[ModuleInfo, ast.ClassDef]]:
        if not name:
            return None
        hits = self.class_index.get(name)
        return hits[0] if hits else None

    # --------------------------------------------------------- derived models
    def protocol(self) -> ProtocolModel:
        if self._protocol is None:
            self._protocol = _build_protocol_model(self)
        return self._protocol

    def threads(self) -> ThreadModel:
        if self._threads is None:
            self._threads = _build_thread_model(self)
        return self._threads

    def flow(self) -> FlowModel:
        if self._flow is None:
            self._flow = _build_flow_model(self)
        return self._flow

    def resources(self) -> ResourceModel:
        if self._resources is None:
            self._resources = _build_resource_model(self)
        return self._resources

    # ------------------------------------------------------------ msg resolve
    def resolve_msg(self, mod: ModuleInfo, node: ast.AST,
                    constants: Dict[str, str]) -> Tuple[Optional[str], bool]:
        """(message value, was_raw_string) for a msg-type expression:
        a string literal, ``P.NAME`` where P aliases the protocol
        module, or a bare NAME from ``from .protocol import NAME``."""
        s = _const_str(node)
        if s is not None:
            return s, True
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            alias = mod.module_aliases.get(node.value.id)
            if alias is not None and node.attr in constants:
                return constants[node.attr], False
            return None, False
        if isinstance(node, ast.Name):
            origin = mod.ctx.import_aliases.get(node.id, "")
            if origin.split(".")[-1] == node.id and node.id in constants:
                return constants[node.id], False
        return None, False


def session_for(paths: Sequence[str],
                overrides: Optional[Dict[str, str]] = None) -> ProjectSession:
    """Build a session over files/directories, with optional source
    overrides (used by revert tests to lint a modified copy of one real
    file against the rest of the live tree)."""
    from .core import iter_python_files, parse_cached

    overrides = overrides or {}
    contexts = []
    for p in iter_python_files(paths):
        try:
            if p in overrides:
                contexts.append(FileContext.parse(p, overrides[p]))
            else:
                contexts.append(parse_cached(p))
        except (SyntaxError, UnicodeDecodeError):
            continue
    return ProjectSession(contexts)


# ===================================================== protocol model builder


def _protocol_constants(mod: ModuleInfo) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in mod.ctx.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id.isupper()
        ):
            v = _const_str(node.value)
            if v is not None:
                out[node.targets[0].id] = v
    return out


def _literal_dict_keys(node: ast.AST) -> Optional[Set[str]]:
    """Keys of a dict literal; None when any key is non-constant or a
    ``**`` spread is present (opaque)."""
    if not isinstance(node, ast.Dict):
        return None
    keys: Set[str] = set()
    for k in node.keys:
        if k is None:                    # ** spread
            return None
        s = _const_str(k)
        if s is None:
            return None
        keys.add(s)
    return keys


def _item_literal_keys(node: ast.AST) -> Optional[FrozenSet[str]]:
    """Item keys when ``node`` builds a list of dict literals — a
    ``[{...}, ...]`` literal or a ``[{...} for ...]`` comprehension.
    Only keys every element carries count (intersection), so a handler
    relying on one is guaranteed it on each item. None = not a tracked
    vector value."""
    if isinstance(node, ast.List) and node.elts:
        elts = node.elts
    elif isinstance(node, ast.ListComp):
        elts = [node.elt]
    else:
        return None
    keys: Optional[Set[str]] = None
    for e in elts:
        k = _literal_dict_keys(e)
        if k is None:
            return None
        keys = set(k) if keys is None else keys & k
    return frozenset(keys) if keys else None


def _tracked_item_keys(fn: ast.AST, call: ast.Call,
                       payload_node: ast.AST) -> Dict[str, FrozenSet[str]]:
    """Vector values inside a send payload: payload key -> item keys,
    for every payload entry whose value is a tracked list-of-dicts.
    Covers the same payload shapes _tracked_payload_keys follows — a
    dict literal at the call, or a local dict augmented by
    ``var["k"] = [...]`` before the send."""
    out: Dict[str, FrozenSet[str]] = {}

    def harvest_dict(d: ast.AST) -> None:
        if not isinstance(d, ast.Dict):
            return
        for k, v in zip(d.keys, d.values):
            s = k is not None and _const_str(k)
            if not s:
                continue
            iks = _item_literal_keys(v)
            if iks is not None:
                out[s] = iks

    harvest_dict(payload_node)
    if not isinstance(payload_node, ast.Name):
        return out
    name = payload_node.id
    for node in ast.walk(fn):
        line = getattr(node, "lineno", None)
        if line is None or line > call.lineno:
            continue
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == name:
                harvest_dict(node.value)
            elif (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id == name
            ):
                s = _const_str(t.slice)
                if s:
                    iks = _item_literal_keys(node.value)
                    if iks is not None:
                        out[s] = iks
    return out


def _tracked_payload_keys(fn: ast.AST, call: ast.Call,
                          payload_node: ast.AST,
                          depth: int = 0) -> Optional[Set[str]]:
    """Payload keys for a send site (see :class:`SendSite.keys`)."""
    if depth > 2:
        return None
    direct = _literal_dict_keys(payload_node)
    if direct is not None:
        return direct
    if (isinstance(payload_node, ast.Call)
            and _call_name(payload_node) == "dict"):
        base: Set[str] = set()
        if payload_node.args:
            if len(payload_node.args) != 1:
                return None
            inner = _literal_dict_keys(payload_node.args[0])
            if inner is None:
                inner = _tracked_payload_keys(
                    fn, call, payload_node.args[0], depth + 1)
            if inner is None:
                return None
            base = set(inner)
        for k in payload_node.keywords:
            if k.arg is None:
                return None
            base.add(k.arg)
        return base
    if not isinstance(payload_node, ast.Name):
        return None
    name = payload_node.id
    keys: Optional[Set[str]] = None
    opaque = False
    for node in ast.walk(fn):
        line = getattr(node, "lineno", None)
        if line is None or line > call.lineno:
            continue
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    k = _literal_dict_keys(node.value)
                    if k is None and (
                        isinstance(node.value, ast.Call)
                        and _call_name(node.value) == "dict"
                    ):
                        k = _tracked_payload_keys(
                            fn, call, node.value, depth + 1)
                    keys, opaque = k, k is None
                elif (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == name
                ):
                    s = _const_str(t.slice)
                    if s is None:
                        opaque = True
                    elif keys is not None:
                        keys.add(s)
        elif isinstance(node, ast.Call) and node is not call:
            # name.update(...) mutates it opaquely; passing the name to
            # any other call may too (the callee can add/remove keys)
            if (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == name
            ):
                if node.func.attr not in ("get", "pop", "keys", "items",
                                          "values", "copy"):
                    opaque = True
            else:
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id == name:
                        opaque = True
                for kw in node.keywords:
                    if isinstance(kw.value, ast.Name) and kw.value.id == name:
                        opaque = True
    if opaque or keys is None:
        return None
    return keys


def _find_sends(session: ProjectSession, mod: ModuleInfo,
                constants: Dict[str, str]) -> List[SendSite]:
    out: List[SendSite] = []
    for fn in _functions_in(mod.ctx.tree):
        qual = mod.qualnames.get(id(fn), fn.name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            api = _call_name(node)
            if api == "_reply":
                keys: Optional[Set[str]] = {"req_id"}
                for k in node.keywords:
                    if k.arg is None:
                        keys = None
                        break
                    keys.add(k.arg)
                out.append(SendSite(
                    module=mod, line=node.lineno, msg="reply", symbol=qual,
                    keys=frozenset(keys) if keys is not None else None,
                    via="_reply", raw_string=False,
                ))
                continue
            msg = raw = payload_node = None
            msgs: List[Tuple[str, bool]] = []
            if api in SEND_APIS:
                for i, a in enumerate(node.args[:2]):
                    m, r = session.resolve_msg(mod, a, constants)
                    if m is None and isinstance(a, ast.Name):
                        # a local like `msg = P.EXEC_ACTOR_CREATE if
                        # spec.is_actor_create else P.EXEC_TASK`: every
                        # resolvable value assigned to it counts as sent
                        vals = _local_msg_values(
                            session, mod, fn, node, a.id, constants)
                        if vals:
                            msgs = vals
                            if len(node.args) > i + 1:
                                payload_node = node.args[i + 1]
                            break
                    if m is not None:
                        msg, raw = m, r
                        if len(node.args) > i + 1:
                            payload_node = node.args[i + 1]
                        break
            elif api == "dumps_frame" and len(node.args) == 1:
                tup = node.args[0]
                if isinstance(tup, ast.Tuple) and len(tup.elts) == 2:
                    m, r = session.resolve_msg(mod, tup.elts[0], constants)
                    if m is not None:
                        msg, raw = m, r
                        payload_node = tup.elts[1]
            elif api == "append" and len(node.args) == 1:
                # batch coalescing: a (msg_type, payload) tuple pushed
                # onto a send buffer goes out inside the next "batch"
                # frame — that append IS the send site (client.flush()'s
                # release_owned ride-along). Gated on the buffer's name
                # so data-shaped tuple appends elsewhere don't register.
                tup = node.args[0]
                f = node.func
                base = f.value if isinstance(f, ast.Attribute) else None
                base_name = self_attr(base) or (
                    base.id if isinstance(base, ast.Name) else None)
                if (
                    isinstance(tup, ast.Tuple)
                    and len(tup.elts) == 2
                    and base_name is not None
                    and any(h in base_name.lower()
                            for h in ("send", "outbox", "out_buf"))
                ):
                    m, r = session.resolve_msg(mod, tup.elts[0], constants)
                    if m is not None:
                        msg, raw = m, r
                        payload_node = tup.elts[1]
            if msg is not None:
                msgs = [(msg, raw)]
            keys = None
            item_keys: Dict[str, FrozenSet[str]] = {}
            if msgs and payload_node is not None:
                keys = _tracked_payload_keys(fn, node, payload_node)
                item_keys = _tracked_item_keys(fn, node, payload_node)
                if keys is not None and api == "request":
                    # CoreClient.request() stamps the req_id itself
                    # (payload = dict(payload, req_id=req_id))
                    keys = set(keys) | {"req_id"}
            for m, r in msgs:
                if m in FRAMING_TYPES or _is_internal(m):
                    continue
                out.append(SendSite(
                    module=mod, line=node.lineno, msg=m, symbol=qual,
                    keys=frozenset(keys) if keys is not None else None,
                    via=api, raw_string=r, item_keys=item_keys,
                ))
    return out


def _local_msg_values(session: ProjectSession, mod: ModuleInfo,
                      fn: ast.AST, call: ast.Call, name: str,
                      constants: Dict[str, str]) -> List[Tuple[str, bool]]:
    out: List[Tuple[str, bool]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        if getattr(node, "lineno", 0) > call.lineno:
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        candidates = [node.value]
        if isinstance(node.value, ast.IfExp):
            candidates = [node.value.body, node.value.orelse]
        for c in candidates:
            m, r = session.resolve_msg(mod, c, constants)
            if m is not None and (m, r) not in out:
                out.append((m, r))
    return out


# ------------------------------------------------------------ handler bodies

_CONDITIONAL_BODIES = (
    ("body", ast.If), ("orelse", ast.If),
    ("body", ast.IfExp), ("orelse", ast.IfExp),
    ("body", ast.While), ("orelse", ast.While),
    ("body", ast.For), ("orelse", ast.For),
)


def _conditional_ids(scope_nodes: Sequence[ast.AST]) -> Set[int]:
    """ids of nodes that may not execute on every entry into the scope:
    anything inside an if/else arm, loop body, try block/handler, the
    right side of a short-circuit, or a comprehension."""
    out: Set[int] = set()

    def mark(n: ast.AST) -> None:
        for sub in ast.walk(n):
            out.add(id(sub))

    for top in scope_nodes:
        for n in ast.walk(top):
            if isinstance(n, (ast.If, ast.While, ast.For)):
                for s in list(n.body) + list(n.orelse):
                    mark(s)
            elif isinstance(n, ast.IfExp):
                mark(n.body)
                mark(n.orelse)
            elif isinstance(n, ast.Try):
                for s in (list(n.body) + list(n.orelse)
                          + list(n.finalbody)):
                    mark(s)
                for h in n.handlers:
                    mark(h)
            elif isinstance(n, ast.BoolOp):
                for v in n.values[1:]:
                    mark(v)
            elif isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                mark(n)
    return out


class _PayloadReads:
    def __init__(self) -> None:
        self.required: Set[str] = set()
        self.read: Set[str] = set()
        self.opaque = False
        # vector payloads: payload key -> reads of the loop variable of
        # a ``for t in payload[k]:`` loop (t["x"] per-item subscripts)
        self.item: Dict[str, "_PayloadReads"] = {}


def _collect_payload_reads(
    mod: ModuleInfo,
    methods: Dict[str, ast.FunctionDef],
    scope_nodes: Sequence[ast.AST],
    payload_name: str,
    acc: _PayloadReads,
    visited: Set[str],
    depth: int = 0,
) -> None:
    """Key reads of ``payload_name`` within ``scope_nodes``, following
    ``self.m(payload)`` calls into same-class methods (the repo's
    handler-helper idiom) up to a small depth."""
    cond = _conditional_ids(scope_nodes)
    for top in scope_nodes:
        for node in ast.walk(top):
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == payload_name
            ):
                key = _const_str(node.slice)
                if key is None:
                    continue
                if isinstance(node.ctx, ast.Load):
                    acc.read.add(key)
                    if id(node) not in cond:
                        acc.required.add(key)
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == payload_name
                ):
                    if f.attr in ("get", "setdefault"):
                        k = node.args and _const_str(node.args[0])
                        if k:
                            acc.read.add(k)
                    elif f.attr == "pop":
                        k = node.args and _const_str(node.args[0])
                        if k:
                            acc.read.add(k)
                            if len(node.args) == 1 and id(node) not in cond:
                                acc.required.add(k)
                    elif f.attr in ("items", "keys", "values", "copy"):
                        acc.opaque = True
                    else:
                        acc.opaque = True
                    continue
                # payload passed onward: into a same-class helper we can
                # follow; anywhere else it escapes our view
                arg_idx = None
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Name) and a.id == payload_name:
                        arg_idx = i
                        break
                passes_kw = any(
                    isinstance(kw.value, ast.Name)
                    and kw.value.id == payload_name
                    for kw in node.keywords
                )
                if arg_idx is None and not passes_kw:
                    continue
                callee = None
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                ):
                    callee = methods.get(f.attr)
                if (callee is None or passes_kw or depth >= 3
                        or callee.name in visited):
                    acc.opaque = True
                    continue
                params = [a.arg for a in callee.args.args]
                pidx = arg_idx + 1  # skip self
                if pidx >= len(params):
                    acc.opaque = True
                    continue
                visited.add(callee.name)
                sub = _PayloadReads()
                _collect_payload_reads(
                    mod, methods, list(callee.body), params[pidx], sub,
                    visited, depth + 1,
                )
                acc.read |= sub.read
                acc.opaque = acc.opaque or sub.opaque
                if id(node) not in cond:
                    acc.required |= sub.required
                for pk, sv in sub.item.items():
                    dst = acc.item.setdefault(pk, _PayloadReads())
                    dst.read |= sv.read
                    dst.opaque = dst.opaque or sv.opaque
                    if id(node) not in cond:
                        dst.required |= sv.required
            elif (
                isinstance(node, ast.Compare)
                and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and isinstance(node.comparators[0], ast.Name)
                and node.comparators[0].id == payload_name
            ):
                k = _const_str(node.left)
                if k:
                    acc.read.add(k)
            elif isinstance(node, (ast.Assign, ast.Return, ast.For)):
                # ``for t in payload["k"]:`` — a vector read: collect
                # the loop variable's per-item subscripts so bulk-frame
                # senders can be checked against them (the subscript on
                # payload itself already registered "k" as a read above)
                if (
                    isinstance(node, ast.For)
                    and isinstance(node.iter, ast.Subscript)
                    and isinstance(node.iter.value, ast.Name)
                    and node.iter.value.id == payload_name
                    and isinstance(node.target, ast.Name)
                ):
                    pk = _const_str(node.iter.slice)
                    if pk:
                        sub = acc.item.setdefault(pk, _PayloadReads())
                        got = _PayloadReads()
                        _collect_payload_reads(
                            mod, methods, list(node.body), node.target.id,
                            got, visited, depth + 1,
                        )
                        sub.read |= got.read
                        sub.opaque = sub.opaque or got.opaque
                        if id(node) not in cond:
                            sub.required |= got.required
                        continue
                # payload stored, returned, or iterated: escapes
                vals = []
                if isinstance(node, ast.Assign):
                    vals = [node.value]
                elif isinstance(node, ast.Return) and node.value is not None:
                    vals = [node.value]
                elif isinstance(node, ast.For):
                    vals = [node.iter]
                for v in vals:
                    if isinstance(v, ast.Name) and v.id == payload_name:
                        acc.opaque = True
                    elif (
                        isinstance(v, (ast.Tuple, ast.List))
                        and any(
                            isinstance(e, ast.Name) and e.id == payload_name
                            for e in v.elts
                        )
                    ):
                        acc.opaque = True


def _handler_from_method(mod: ModuleInfo, cls: ast.ClassDef,
                         fn: ast.FunctionDef, msg: str,
                         raw: bool) -> Handler:
    methods = mod.methods(cls)
    params = [a.arg for a in fn.args.args]
    payload_name = params[-1] if len(params) > 1 else None
    acc = _PayloadReads()
    if payload_name:
        _collect_payload_reads(
            mod, methods, list(fn.body), payload_name, acc, {fn.name})
    return Handler(
        module=mod, line=fn.lineno, msg=msg,
        symbol=f"{cls.name}.{fn.name}",
        required_keys=frozenset(acc.required),
        read_keys=frozenset(acc.read),
        opaque=acc.opaque or payload_name is None,
        raw_string=raw,
        item_required={k: frozenset(v.required)
                       for k, v in acc.item.items() if v.required},
        item_read={k: frozenset(v.read)
                   for k, v in acc.item.items() if v.read},
    )


def _prefix_table(cls: ast.ClassDef, v: ast.AST) -> Optional[str]:
    """The ``_on_`` prefix when ``v`` is the convention table
    ``{name[len(prefix):]: getattr(self, name) for name in dir(...)
    if name.startswith(prefix)}``; else None."""
    if not isinstance(v, ast.DictComp):
        return None
    if _call_name(v.value) != "getattr":
        return None
    for gen in v.generators:
        for test in gen.ifs:
            if (
                isinstance(test, ast.Call)
                and isinstance(test.func, ast.Attribute)
                and test.func.attr == "startswith"
                and test.args
            ):
                prefix = _const_str(test.args[0])
                if prefix:
                    return prefix
    return None


def _extract_chain_compare(test: ast.AST):
    """(var_name, [msg exprs]) for ``var == X`` / ``var in (X, Y)``
    tests, looking through a leading ``and``."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        test = test.values[0]
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
        return None
    left, op, comp = test.left, test.ops[0], test.comparators[0]
    if not isinstance(left, ast.Name):
        return None
    if isinstance(op, ast.Eq):
        return left.id, [comp]
    if isinstance(op, ast.In) and isinstance(comp, (ast.Tuple, ast.List,
                                                    ast.Set)):
        return left.id, list(comp.elts)
    return None


def _payload_partner(fn: ast.FunctionDef, msg_var: str) -> Optional[str]:
    """The payload variable travelling with ``msg_var``: the second
    target of a ``msg_var, payload = ...`` unpack, else the last
    parameter that isn't self/conn/the msg var."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Tuple)
                    and len(t.elts) == 2
                    and isinstance(t.elts[0], ast.Name)
                    and t.elts[0].id == msg_var
                    and isinstance(t.elts[1], ast.Name)
                ):
                    return t.elts[1].id
    params = [a.arg for a in fn.args.args
              if a.arg not in ("self", "conn", msg_var)]
    return params[-1] if params else None


def _elif_chain(session: ProjectSession, mod: ModuleInfo,
                cls: Optional[ast.ClassDef], fn: ast.FunctionDef,
                constants: Dict[str, str],
                ) -> Tuple[Optional[DispatchTable], List[Handler]]:
    arms: List[Tuple[str, bool, ast.If]] = []   # (msg, raw, branch)
    msg_var_seen: Optional[str] = None
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        got = _extract_chain_compare(node.test)
        if got is None:
            continue
        var, exprs = got
        if var not in MSG_VAR_NAMES:
            continue
        for e in exprs:
            m, raw = session.resolve_msg(mod, e, constants)
            if m is None or m in FRAMING_TYPES or _is_internal(m):
                continue
            msg_var_seen = var
            arms.append((m, raw, node))
    if len({m for m, _r, _n in arms}) < 2:
        return None, []
    payload_name = _payload_partner(fn, msg_var_seen)
    methods = mod.methods(cls) if cls is not None else {}
    qual = mod.qualnames.get(id(fn), fn.name)
    handlers = []
    for msg, raw, branch in arms:
        acc = _PayloadReads()
        if payload_name:
            _collect_payload_reads(
                mod, methods, list(branch.body), payload_name, acc,
                {fn.name})
        handlers.append(Handler(
            module=mod, line=branch.lineno, msg=msg, symbol=qual,
            required_keys=frozenset(acc.required),
            read_keys=frozenset(acc.read),
            opaque=acc.opaque or payload_name is None,
            raw_string=raw,
            item_required={k: frozenset(v.required)
                           for k, v in acc.item.items() if v.required},
            item_read={k: frozenset(v.read)
                       for k, v in acc.item.items() if v.read},
        ))
    table = DispatchTable(
        module=mod, line=fn.lineno, kind="elif", owner=qual,
        msgs=frozenset({m for m, _r, _n in arms}),
    )
    return table, handlers


def _find_tables(session: ProjectSession, mod: ModuleInfo,
                 constants: Dict[str, str],
                 ) -> Tuple[List[DispatchTable], List[Handler]]:
    tables: List[DispatchTable] = []
    handlers: List[Handler] = []
    fn_index = _FnIndex(mod)
    for cls_name, cls in mod.classes.items():
        methods = mod.methods(cls)
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, value = node.target, node.value
            else:
                continue
            if not (self_attr(tgt) or isinstance(tgt, ast.Name)):
                continue
            # convention table: {name[len("_on_"):]: getattr(self, name)
            #                    for name in dir(...) ...}
            prefix = _prefix_table(cls, value)
            if prefix is not None:
                msgs = set()
                for mname, meth in methods.items():
                    if not mname.startswith(prefix) or mname == prefix:
                        continue
                    msg = mname[len(prefix):]
                    msgs.add(msg)
                    handlers.append(
                        _handler_from_method(mod, cls, meth, msg, False))
                if msgs:
                    tables.append(DispatchTable(
                        module=mod, line=node.lineno, kind="prefix",
                        owner=cls_name, msgs=frozenset(msgs),
                    ))
                continue
            # dict-literal table: {P.REPLY: self._on_reply, ...}
            if isinstance(value, ast.Dict) and value.keys:
                entries = []
                ok = True
                for k, v in zip(value.keys, value.values):
                    if k is None:
                        ok = False
                        break
                    msg, raw = session.resolve_msg(mod, k, constants)
                    target = self_attr(v)
                    if msg is None or target is None:
                        ok = False
                        break
                    entries.append((msg, raw, target))
                # a handler table maps every entry to a method of this
                # class — a dict of plain self-attributes (config
                # snapshots, serve deployment options) is not dispatch
                if ok and entries and all(
                    t in methods for _m, _r, t in entries
                ):
                    msgs = set()
                    for msg, raw, target in entries:
                        if msg in FRAMING_TYPES or _is_internal(msg):
                            continue
                        msgs.add(msg)
                        handlers.append(_handler_from_method(
                            mod, cls, methods[target], msg, raw))
                    if msgs:
                        tables.append(DispatchTable(
                            module=mod, line=node.lineno, kind="dict",
                            owner=cls_name, msgs=frozenset(msgs),
                        ))
    # if/elif chains, in methods and module functions
    for fn in _functions_in(mod.ctx.tree):
        cls_name, _f = fn_index.owner.get(id(fn), (None, None))
        cls = mod.classes.get(cls_name) if cls_name else None
        table, hs = _elif_chain(session, mod, cls, fn, constants)
        if table is not None:
            tables.append(table)
            handlers.extend(hs)
    return tables, handlers


def _routing_sets(session: ProjectSession, mod: ModuleInfo,
                  constant_values: Set[str]) -> List[RoutingSet]:
    out: List[RoutingSet] = []
    sharded_mod = bool(
        "shard" in mod.basename
        or any(_REACTOR_CLASS.search(c) for c in mod.classes)
    )
    for node in mod.ctx.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and tgt.id.isupper()):
            continue
        v = node.value
        if isinstance(v, ast.Call) and _call_name(v) in ("frozenset", "set"):
            if len(v.args) != 1:
                continue
            v = v.args[0]
        if not isinstance(v, ast.Set):
            continue
        msgs = set()
        ok = True
        for e in v.elts:
            s = _const_str(e)
            if s is None:
                ok = False
                break
            msgs.add(s)
        if not ok or len(msgs) < 3:
            continue
        # a routing set routes MESSAGES: most elements must be known
        # protocol values or the set is some other string table (an
        # allow-list, a keyword set) that happens to live nearby
        if constant_values:
            known = len(msgs & constant_values)
            if known / len(msgs) < 0.8:
                continue
        out.append(RoutingSet(
            module=mod, line=node.lineno, name=tgt.id,
            msgs=frozenset(msgs), sharded=sharded_mod,
        ))
    return out


def _build_protocol_model(session: ProjectSession) -> ProtocolModel:
    proto_mod: Optional[ModuleInfo] = None
    constants: Dict[str, str] = {}
    for mod in session.by_basename.get("protocol", []):
        consts = _protocol_constants(mod)
        if consts:
            proto_mod = mod
            constants = consts
            break
    sends: List[SendSite] = []
    handlers: List[Handler] = []
    tables: List[DispatchTable] = []
    routing: List[RoutingSet] = []
    compared: Set[str] = set()
    for mod in session.modules:
        sends.extend(_find_sends(session, mod, constants))
        t, h = _find_tables(session, mod, constants)
        tables.extend(t)
        handlers.extend(h)
        routing.extend(_routing_sets(session, mod, set(constants.values())))
        for node in ast.walk(mod.ctx.tree):
            if not (isinstance(node, ast.Compare) and len(node.ops) == 1):
                continue
            if not isinstance(node.ops[0], (ast.Eq, ast.NotEq, ast.In,
                                            ast.NotIn)):
                continue
            comps = [node.comparators[0], node.left]
            exprs: List[ast.AST] = []
            for c in comps:
                if isinstance(c, (ast.Tuple, ast.List, ast.Set)):
                    exprs.extend(c.elts)
                else:
                    exprs.append(c)
            for e in exprs:
                m, _r = session.resolve_msg(mod, e, constants)
                if m is not None:
                    compared.add(m)
    return ProtocolModel(
        constants=constants,
        constant_values=set(constants.values()),
        protocol_module=proto_mod,
        sends=sends,
        handlers=handlers,
        tables=tables,
        routing_sets=routing,
        compared=compared,
    )


# ======================================================= thread model builder


def _ctor_class(node: ast.AST) -> Optional[str]:
    """Class name constructed by ``node``: ``Cls(...)``,
    ``[Cls(...) for ...]``, ``[Cls(...), ...]``."""
    if isinstance(node, ast.Call):
        n = _call_name(node)
        if n and n[:1].isupper():
            return n
    if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return _ctor_class(node.elt)
    if isinstance(node, (ast.List, ast.Tuple)) and node.elts:
        names = {_ctor_class(e) for e in node.elts}
        if len(names) == 1:
            return names.pop()
    return None


def _annotation_class(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Name) and node.id[:1].isupper():
        return node.id
    if isinstance(node, ast.Attribute) and node.attr[:1].isupper():
        return node.attr
    if isinstance(node, ast.Subscript):  # List[Cls] / Optional[Cls]
        return _annotation_class(node.slice)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        # string annotation: 'ReactorShard' / List["ReactorShard"]
        name = node.value.strip("'\"").split("[")[-1].rstrip("]").strip(
            "'\"")
        if name[:1].isupper():
            return name
    return None


def _is_thread_subclass(cls: ast.ClassDef) -> bool:
    for b in cls.bases:
        tail = b.attr if isinstance(b, ast.Attribute) else (
            b.id if isinstance(b, ast.Name) else "")
        if tail == "Thread":
            return True
    return False


def _thread_targets(node: ast.Call) -> List[str]:
    """Self-method names referenced by a Thread(...) construction's
    ``target=`` expression (looks through ``a if c else b``)."""
    out: List[str] = []
    for kw in node.keywords:
        if kw.arg != "target":
            continue
        for sub in ast.walk(kw.value):
            a = self_attr(sub)
            if a is not None:
                out.append(a)
    return out


def _call_edges(methods: Dict[str, ast.FunctionDef]) -> Dict[str, Set[str]]:
    """Intra-class call graph: method -> self-methods it calls or
    references (a bound-method reference handed to a timer/executor
    runs in the consumer's domain, so references count as edges)."""
    edges: Dict[str, Set[str]] = {m: set() for m in methods}
    for mname, fn in methods.items():
        for node in ast.walk(fn):
            a = self_attr(node)
            if a is not None and a in methods:
                edges[mname].add(a)
    return edges


def _build_thread_model(session: ProjectSession) -> ThreadModel:
    protocol = session.protocol()
    # (module, class name) -> handler method names (dict/prefix
    # tables). Module-scoped so two same-named owner classes in
    # different modules don't pool their handler sets.
    table_handlers: Dict[Tuple[int, str], Set[str]] = {}
    for t in protocol.tables:
        if t.kind == "elif":
            continue
        owner = t.owner
        hs = table_handlers.setdefault((id(t.module), owner), set())
        for h in protocol.handlers:
            if h.module is t.module and h.symbol.startswith(owner + "."):
                hs.add(h.symbol.split(".", 1)[1])
    classes: Dict[str, ClassThreads] = {}
    by_name: Dict[str, List[ClassThreads]] = {}
    for mod in session.modules:
        for cls_name, cls in mod.classes.items():
            info = ClassThreads(
                module=mod, cls=cls,
                qual=f"{mod.basename}.{cls_name}",
            )
            methods = mod.methods(cls)
            # ---- attribute types + channel attrs
            for fn in methods.values():
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign):
                        ctor = _ctor_class(node.value)
                        for t in node.targets:
                            a = self_attr(t)
                            if a is None:
                                continue
                            if ctor:
                                info.attr_types.setdefault(a, ctor)
                                if ctor in CHANNEL_CTORS:
                                    info.channel_attrs.add(a)
                            if _channel_name(a):
                                info.channel_attrs.add(a)
                    elif isinstance(node, ast.AnnAssign):
                        a = self_attr(node.target)
                        if a is not None:
                            ann = _annotation_class(node.annotation)
                            if ann:
                                info.attr_types.setdefault(a, ann)
                            if _channel_name(a):
                                info.channel_attrs.add(a)
            # ---- seeds
            seeds: Dict[str, Set[str]] = {}
            ctor_labels: List[str] = []

            def seed(method: str, label: str) -> None:
                if method in methods:
                    seeds.setdefault(method, set()).add(label)

            for mname, fn in methods.items():
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Call)
                            and _call_name(node) == "Thread"):
                        targets = _thread_targets(node)
                        if not targets:
                            continue
                        label = f"thread:{info.qual}.{targets[0]}"
                        ctor_labels.append(label)
                        for t in targets:
                            seed(t, label)
            if _is_thread_subclass(cls) or _REACTOR_CLASS.search(cls_name):
                seed("run", f"thread:{info.qual}.run")
            if "_read_loop" in methods:
                seed("_read_loop", f"thread:{info.qual}._read_loop")
            # timer callbacks run on the class's main loop thread
            main_label = ctor_labels[0] if len(ctor_labels) >= 1 else None
            if main_label is not None:
                for mname, fn in methods.items():
                    for node in ast.walk(fn):
                        if (isinstance(node, ast.Call)
                                and _call_name(node) in ("_add_timer",
                                                         "add_timer")):
                            for a in node.args:
                                for sub in ast.walk(a):
                                    cb = self_attr(sub)
                                    if cb is not None and cb in methods:
                                        seed(cb, main_label)
            # ---- propagate through the intra-class call graph, then
            # fold dispatch-table handlers into their dispatcher's domain
            edges = _call_edges(methods)

            def propagate() -> None:
                domains = info.domains
                for m, labels in seeds.items():
                    domains.setdefault(m, set()).update(labels)
                changed = True
                while changed:
                    changed = False
                    for m, callees in edges.items():
                        src = domains.get(m)
                        if not src:
                            continue
                        for c in callees:
                            dst = domains.setdefault(c, set())
                            if not src <= dst:
                                dst |= src
                                changed = True

            propagate()
            hmethods = table_handlers.get((id(mod), cls_name), set())
            if hmethods:
                # the dispatcher that consumes the table already has the
                # right domain after propagation (e.g. _dispatch_inbound
                # under the reader thread); handler methods inherit it.
                # Fall back to the class main loop, then a synthetic
                # label, so handler-vs-handler conflicts still surface
                # in classes whose thread plumbing we can't see.
                inherited: Set[str] = set()
                for cand in ("_dispatch_msg", "_dispatch_inbound",
                             "_dispatch", "_handle"):
                    if info.domains.get(cand):
                        inherited = set(info.domains[cand])
                        break
                if not inherited and main_label is not None:
                    inherited = {main_label}
                if not inherited:
                    inherited = {f"handlers:{info.qual}"}
                for h in hmethods:
                    seeds.setdefault(h, set()).update(inherited)
                propagate()
            classes[info.qual] = info
            by_name.setdefault(cls_name, []).append(info)
    return ThreadModel(classes=classes, by_name=by_name)


# ========================================================== flow model builder
#
# The GL015 pass needs what no per-file rule can see: whether a SYNC
# helper called from a coroutine eventually parks the thread. Blocking
# recognition is shared with GL003 (same dotted table, same no-timeout
# method forms) so the two rules' notions of "a blocking op" cannot
# diverge; this builder adds the transitive closure over the project
# call graph plus the slow-thread-lock roots.

_TRACE_READ_CALLS = frozenset({"current_context", "begin_trace"})
_CLOSURE_DISPATCH_THREAD = frozenset({"Thread"})


def _local_nodes(fn: ast.AST):
    """Nodes lexically inside ``fn``, not descending into nested
    defs/lambdas/classes (their bodies run where they are *called*)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


def _callee_key(session: ProjectSession, mod: ModuleInfo,
                cls_name: Optional[str],
                methods: Dict[str, ast.FunctionDef],
                call: ast.Call) -> Optional[str]:
    """Flow-graph key of the function a call resolves to: a same-class
    ``self.m()``, a same-module ``fn()``, a from-imported ``fn()``, or
    a ``mod_alias.fn()`` into another session module."""
    f = call.func
    a = self_attr(f)
    if a is not None:
        if cls_name is not None and a in methods:
            return f"{mod.basename}.{cls_name}.{a}"
        return None
    if isinstance(f, ast.Name):
        if f.id in mod.functions:
            return f"{mod.basename}.{f.id}"
        origin = mod.ctx.import_aliases.get(f.id, "")
        if "." in origin:
            mpath, fname = origin.rsplit(".", 1)
            tail = mpath.split(".")[-1]
            for tm in session.by_basename.get(tail, []):
                if fname in tm.functions:
                    return f"{tm.basename}.{fname}"
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        alias = mod.module_aliases.get(f.value.id)
        if alias is not None:
            for tm in session.by_basename.get(alias, []):
                if f.attr in tm.functions:
                    return f"{tm.basename}.{f.attr}"
    return None


def _is_none_guard(test: ast.AST) -> bool:
    """``<name> is None`` — the no-trace fast path: inside its body a
    closure has no ambient context worth re-pushing."""
    return (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.Is)
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    )


def _calls_push_context(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _call_name(sub) == "push_context":
            return True
    return False


def _unsafe_ctx_dispatches(fn: ast.AST) -> List[Tuple[int, str]]:
    """(line, closure name) for every local lambda/nested-def handed to
    ``run_in_executor`` / ``Thread(target=)`` without re-pushing the
    trace context, outside an ``if <x> is None:`` no-trace guard.
    Bound-method and partial targets are exempt: the rule exists for
    closures written next to a live trace read (PR 13's hand-fix)."""
    nested: Dict[str, ast.AST] = {
        n.name: n for n in _local_nodes(fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    out: List[Tuple[int, str]] = []

    def closure_of(node: ast.AST) -> Optional[Tuple[str, ast.AST]]:
        if isinstance(node, ast.Lambda):
            return "<lambda>", node
        if isinstance(node, ast.Name) and node.id in nested:
            return node.id, nested[node.id]
        return None

    def check_call(call: ast.Call, guarded: bool) -> None:
        target: Optional[ast.AST] = None
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "run_in_executor":
            if len(call.args) >= 2:
                target = call.args[1]
        elif _call_name(call) in _CLOSURE_DISPATCH_THREAD:
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
        if target is None:
            return
        got = closure_of(target)
        if got is None:
            return
        name, body = got
        if guarded or _calls_push_context(body):
            return
        out.append((call.lineno, name))

    def visit(node: ast.AST, guarded: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.Call):
            check_call(node, guarded)
        if isinstance(node, ast.If) and _is_none_guard(node.test):
            visit(node.test, guarded)
            for s in node.body:
                visit(s, True)
            for s in node.orelse:
                visit(s, guarded)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, guarded)

    for stmt in ast.iter_child_nodes(fn):
        visit(stmt, False)
    return out


def _build_flow_model(session: ProjectSession) -> FlowModel:
    # shared blocking recognition — GL003's tables ARE the roots
    from .checkers.gl003_blocking_async import (
        BLOCKING,
        blocking_method_form,
        local_ctor_kinds,
    )

    functions: Dict[str, FlowFunction] = {}
    for mod in session.modules:
        fn_index = _FnIndex(mod)
        for fn in _functions_in(mod.ctx.tree):
            qual = mod.qualnames.get(id(fn), fn.name)
            key = f"{mod.basename}.{qual}"
            if key in functions:
                continue  # first-hit rule, same as resolve_class
            cls_name, _owner_fn = fn_index.owner.get(id(fn), (None, None))
            cls = mod.classes.get(cls_name) if cls_name else None
            methods = mod.methods(cls) if cls is not None else {}
            ff = FlowFunction(
                module=mod, node=fn, key=key, qual=qual,
                is_async=isinstance(fn, ast.AsyncFunctionDef),
                cls_name=cls_name,
            )
            awaited = {
                id(sub)
                for n in _local_nodes(fn)
                if isinstance(n, ast.Await)
                for sub in ast.walk(n)
            }
            stmt_calls = {
                id(n.value)
                for n in _local_nodes(fn)
                if isinstance(n, ast.Expr) and isinstance(n.value, ast.Call)
            }
            kinds = local_ctor_kinds(fn)
            for node in _local_nodes(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        a = self_attr(item.context_expr)
                        if a is not None and is_lockish(a) and cls_name:
                            ff.locks.add(f"{mod.basename}.{cls_name}.{a}")
                if not isinstance(node, ast.Call):
                    continue
                tail = _call_name(node)
                if tail in _TRACE_READ_CALLS:
                    ff.reads_trace_ctx = True
                if id(node) not in awaited:
                    name = mod.ctx.resolve(dotted_name(node.func))
                    hint = BLOCKING.get(name or "")
                    if hint is not None:
                        ff.blocking.append(
                            (node.lineno, f"blocking `{name}(...)`"))
                    else:
                        form = blocking_method_form(node, kinds)
                        if form is not None:
                            recv, _kind, _fix = form
                            ff.blocking.append((
                                node.lineno,
                                f"no-timeout `{recv}.{node.func.attr}()`",
                            ))
                callee = _callee_key(session, mod, cls_name, methods, node)
                if callee is not None:
                    ff.calls.append((
                        node.lineno, callee,
                        id(node) in awaited, id(node) in stmt_calls,
                    ))
            if ff.reads_trace_ctx:
                ff.ctx_unsafe_dispatches = _unsafe_ctx_dispatches(fn)
            functions[key] = ff

    # slow-thread locks: a thread-domain method that performs one of
    # the recognized blocking ops INSIDE `with self.<lock>:` makes that
    # lock a blocking root for everyone else
    tm = session.threads()
    slow: Dict[str, str] = {}
    for key, ff in functions.items():
        if not ff.blocking or ff.cls_name is None:
            continue
        cq = f"{ff.module.basename}.{ff.cls_name}"
        info = tm.classes.get(cq)
        if info is None:
            continue
        mname = ff.qual.rsplit(".", 1)[-1]
        doms = info.domains.get(mname, set())
        if not any(d.startswith("thread:") for d in doms):
            continue
        blines = [ln for ln, _d in ff.blocking]
        for node in _local_nodes(ff.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            end = getattr(node, "end_lineno", node.lineno)
            if not any(node.lineno <= b <= end for b in blines):
                continue
            for item in node.items:
                a = self_attr(item.context_expr)
                if a is not None and is_lockish(a):
                    lock = f"{ff.module.basename}.{ff.cls_name}.{a}"
                    op = next(d for ln, d in ff.blocking
                              if node.lineno <= ln <= end)
                    slow.setdefault(
                        lock,
                        f"held around {op} by {key} "
                        f"(runs on {sorted(doms)[0]})",
                    )
    return FlowModel(functions=functions, slow_thread_locks=slow)


# ====================================================== resource model builder


def _acquire_kind(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        return ACQUIRE_CTORS.get(_call_name(value) or "")
    return None


def _build_resource_model(session: ProjectSession) -> ResourceModel:
    classes: Dict[str, ResourceClass] = {}
    for mod in session.modules:
        for cls_name, cls in mod.classes.items():
            rc = ResourceClass(
                module=mod, cls_name=cls_name,
                qual=f"{mod.basename}.{cls_name}",
            )
            methods = mod.methods(cls)
            # ---- sweep A: typed names (selector ctors, timer pushes)
            sel_names: Set[str] = set()
            for fn in methods.values():
                for node in _local_nodes(fn):
                    if isinstance(node, ast.Assign):
                        if _acquire_kind(node.value) == "selector":
                            for t in node.targets:
                                a = self_attr(t)
                                if a is not None:
                                    sel_names.add(a)
                                elif isinstance(t, ast.Name):
                                    sel_names.add(t.id)
                    elif isinstance(node, ast.Call):
                        tail = _call_name(node)
                        if (
                            tail in ("heappush", "append")
                            and node.args
                        ):
                            a = self_attr(node.args[0]) if tail == "heappush" \
                                else None
                            if tail == "append":
                                f = node.func
                                base = (f.value if isinstance(f, ast.Attribute)
                                        else None)
                                a = self_attr(base) if base is not None else None
                            if a is not None and "timer" in a.lower():
                                rc.timer_attrs.setdefault(a, []).append(
                                    node.lineno)
            # ---- sweep B: aliases, pairing sites, drops, clears, stores
            drops_raw: Dict[str, List[int]] = {}
            clears_raw: Dict[str, List[int]] = {}
            for mname, fn in methods.items():
                # precollect: _local_nodes is unordered (stack walk), and
                # the registry store may be visited before its acquire
                acquired_locals: Set[str] = {
                    t.id
                    for node in _local_nodes(fn)
                    if isinstance(node, ast.Assign)
                    and _acquire_kind(node.value) is not None
                    for t in node.targets
                    if isinstance(t, ast.Name)
                }
                # aliases too: `sel = self._selector` before `sel.unregister`
                for node in _local_nodes(fn):
                    if isinstance(node, ast.Assign):
                        va = self_attr(node.value)
                        if va is not None and va in sel_names:
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    sel_names.add(t.id)
                for node in _local_nodes(fn):
                    if isinstance(node, ast.Assign):
                        v = node.value
                        for t in node.targets:
                            # handle-registry store: self.X[k] = handle
                            if (
                                isinstance(t, ast.Subscript)
                                and isinstance(v, ast.Name)
                                and v.id in acquired_locals
                            ):
                                a = self_attr(t.value)
                                if a is not None:
                                    rc.registry_attrs.setdefault(
                                        a, []).append(node.lineno)
                            # teardown reassign: self.X = [] outside init
                            a = self_attr(t)
                            if (
                                a is not None
                                and mname != "__init__"
                                and isinstance(node.value, (ast.List,
                                                            ast.Dict))
                                and not getattr(node.value, "elts", None)
                                and not getattr(node.value, "keys", None)
                            ):
                                clears_raw.setdefault(a, []).append(
                                    node.lineno)
                    elif isinstance(node, ast.Delete):
                        for t in node.targets:
                            if isinstance(t, ast.Subscript):
                                a = self_attr(t.value)
                                if a is not None:
                                    drops_raw.setdefault(a, []).append(
                                        node.lineno)
                    elif isinstance(node, ast.Call):
                        f = node.func
                        if not isinstance(f, ast.Attribute):
                            continue
                        base = f.value
                        bname = self_attr(base) or (
                            base.id if isinstance(base, ast.Name) else None)
                        if bname is None:
                            continue
                        if f.attr in ("register", "unregister", "close") \
                                and bname in sel_names:
                            if f.attr == "register":
                                rc.register_sites.append(node.lineno)
                            elif f.attr == "unregister":
                                rc.unregister_sites.append(node.lineno)
                            else:
                                rc.selector_close_sites.append(node.lineno)
                        if f.attr in ("pop", "popitem") \
                                and self_attr(base) is not None:
                            drops_raw.setdefault(self_attr(base), []).append(
                                node.lineno)
                        if f.attr == "clear" and self_attr(base) is not None:
                            a = self_attr(base)
                            drops_raw.setdefault(a, []).append(node.lineno)
                            clears_raw.setdefault(a, []).append(node.lineno)
            rc.selector_names = sel_names
            rc.registry_drops = {
                a: drops_raw[a] for a in rc.registry_attrs if a in drops_raw
            }
            rc.timer_clears = {
                a: clears_raw[a] for a in rc.timer_attrs if a in clears_raw
            }
            if (
                rc.selector_names or rc.register_sites or rc.timer_attrs
                or rc.registry_attrs
            ):
                classes[rc.qual] = rc
    return ResourceModel(classes=classes)
