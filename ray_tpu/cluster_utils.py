"""Cluster: a multi-host test/dev harness on one machine.

Parity: python/ray/cluster_utils.py:135 (Cluster/add_node) — spins a
TCP-mode hub (head) plus N node-agent processes, each simulating one
host with its own session dir, resources, and (fake) hostname, so
multi-node scheduling, cross-node objects, STRICT_SPREAD placement, and
multi-process jax.distributed gangs are all exercisable without real
extra hosts. On real multi-host deployments the same agent binary runs
per host with RAY_TPU_HUB_ADDR pointing at the head.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional


class ClusterNode:
    def __init__(self, node_id: str, proc: subprocess.Popen, session_dir: str):
        self.node_id = node_id
        self.proc = proc
        self.session_dir = session_dir


class Cluster:
    """Start a head (in-process hub over TCP) and add simulated hosts."""

    def __init__(
        self,
        head_num_cpus: int = 2,
        head_resources: Optional[Dict[str, float]] = None,
        max_workers: Optional[int] = None,
    ):
        import ray_tpu

        self._ray = ray_tpu
        ctx = ray_tpu.init(
            num_cpus=head_num_cpus,
            resources=head_resources,
            max_workers=max_workers,
            _tcp_hub=True,
        )
        self.address = ctx.address_info["address"]
        assert self.address.startswith("tcp://"), self.address
        self.nodes: List[ClusterNode] = []
        self._counter = 0

    def add_node(
        self,
        *,
        num_cpus: int = 2,
        num_tpus: int = 0,
        resources: Optional[Dict[str, float]] = None,
        hostname: Optional[str] = None,
        max_workers: Optional[int] = None,
        wait: bool = True,
    ) -> ClusterNode:
        self._counter += 1
        node_id = f"node{self._counter}"
        from ray_tpu._private.session import new_session_dir

        session_dir = new_session_dir(f"ray_tpu_{node_id}")
        env = dict(os.environ)
        # the agent (and transitively its workers) must be able to import
        # ray_tpu and the driver's modules regardless of cwd
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = [pkg_parent] + [p for p in sys.path if p]
        if env.get("PYTHONPATH"):
            paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(paths))
        env.update(
            RAY_TPU_HUB_ADDR=self.address,
            RAY_TPU_NODE_ID=node_id,
            RAY_TPU_SESSION_DIR=session_dir,
            RAY_TPU_NUM_CPUS=str(num_cpus),
            RAY_TPU_NUM_TPUS=str(num_tpus),
            # simulate a distinct host: fake hostname, loopback IP
            RAY_TPU_NODE_HOSTNAME=hostname or f"host-{node_id}",
            RAY_TPU_NODE_IP="127.0.0.1",
        )
        if resources:
            env["RAY_TPU_CUSTOM_RESOURCES"] = json.dumps(resources)
        if max_workers:
            env["RAY_TPU_MAX_WORKERS"] = str(max_workers)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_agent"], env=env
        )
        node = ClusterNode(node_id, proc, session_dir)
        self.nodes.append(node)
        if wait:
            self._wait_for_node(node_id)
        return node

    def _wait_for_node(self, node_id: str, timeout: float = 15.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(
                n["node_id"] == node_id and n["alive"]
                for n in self._ray.nodes()
            ):
                return
            time.sleep(0.05)
        raise TimeoutError(f"node {node_id} did not register within {timeout}s")

    def remove_node(self, node: ClusterNode, timeout: float = 10.0) -> None:
        node.proc.terminate()
        try:
            node.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            node.proc.kill()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(
                n["node_id"] == node.node_id and not n["alive"]
                for n in self._ray.nodes()
            ):
                return
            time.sleep(0.05)

    def shutdown(self) -> None:
        import shutil

        for node in self.nodes:
            try:
                node.proc.terminate()
                node.proc.wait(timeout=5)
            except Exception:
                try:
                    node.proc.kill()
                except Exception:
                    pass
            shutil.rmtree(node.session_dir, ignore_errors=True)
        self.nodes = []
        self._ray.shutdown()
