"""Batch iteration with prefetch + HBM staging.

Parity: python/ray/data/iterator.py + _internal/block_batching/ (format
conversion, prefetching). TPU-native: ``device_put`` stages the next
batch into device memory while the current one is being consumed
(double buffering over the host->HBM DMA), which is how a training loop
hides input latency behind compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, List, Optional

import numpy as np

from .block import Block, BlockAccessor

_SENTINEL = object()


def _rebatch(block_refs, batch_size: Optional[int], drop_last: bool) -> Iterator[Block]:
    """Coalesce/slice streamed blocks into exact-size batches."""
    import ray_tpu

    buf: List[Block] = []
    buffered = 0
    for ref in block_refs:
        block = ray_tpu.get(ref)
        n = BlockAccessor.for_block(block).num_rows()
        if n == 0:
            continue
        if batch_size is None:
            yield block
            continue
        buf.append(block)
        buffered += n
        while buffered >= batch_size:
            merged = BlockAccessor.concat(buf)
            acc = BlockAccessor.for_block(merged)
            yield acc.slice(0, batch_size)
            rest = acc.slice(batch_size, acc.num_rows())
            buf = [rest]
            buffered = BlockAccessor.for_block(rest).num_rows()
    if batch_size is None:
        return
    if buffered and not drop_last:
        merged = BlockAccessor.concat(buf)
        if BlockAccessor.for_block(merged).num_rows():
            yield merged


def iter_batches(
    block_refs,
    *,
    batch_size: Optional[int],
    batch_format: str,
    prefetch_batches: int,
    drop_last: bool,
    device_put: Any = None,
) -> Iterator[Any]:
    def produce() -> Iterator[Any]:
        for block in _rebatch(block_refs, batch_size, drop_last):
            batch = BlockAccessor.for_block(block).to_batch(batch_format)
            if device_put is not None:
                import jax

                batch = jax.tree.map(
                    lambda v: jax.device_put(np.ascontiguousarray(v), device_put)
                    if isinstance(v, np.ndarray) and v.dtype != object
                    else v,
                    batch,
                )
            yield batch

    if prefetch_batches <= 0:
        yield from produce()
        return

    q: "queue.Queue" = queue.Queue(maxsize=prefetch_batches)
    err: List[BaseException] = []
    stop = threading.Event()

    def worker():
        try:
            for item in produce():
                # bounded put that aborts if the consumer abandoned the
                # iterator (otherwise this thread would pin prefetched
                # HBM batches for the life of the process)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer side
            err.append(e)
        finally:
            while not stop.is_set():  # consumer still listening
                try:
                    q.put(_SENTINEL, timeout=0.2)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=worker, daemon=True, name="data-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                break
            yield item
        if err:
            raise err[0]
    finally:
        stop.set()
