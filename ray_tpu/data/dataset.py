"""Dataset: the lazy distributed data API.

Parity: python/ray/data/dataset.py (6,080 lines in the reference; the
surface here covers the operations its users reach for: map/map_batches
/filter/flat_map, shuffles/sort/groupby, consumption, splits) +
read_api.py. Everything is lazy: transforms append logical ops;
consumption lowers through build_stages and runs on the streaming
executor (see _internal/executor.py).

TPU-native: ``iter_batches(device_put=...)`` stages columnar numpy
batches straight into HBM with double-buffering — the `num_tpus`
actor-pool stage plus this iterator are the reference's GPU
batch-inference path (§3.5 step 4) re-done for chips.
"""

from __future__ import annotations

import builtins
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import Block, BlockAccessor
from .context import DataContext
from ._internal import plan as L
from ._internal.executor import StreamingExecutor, build_stages


class ActorPoolStrategy:
    """Parity: ray.data.ActorPoolStrategy — pin UDFs to a pool of
    actors (stateful / device-holding UDFs)."""

    def __init__(self, size: Optional[int] = None, min_size: int = 1, max_size: Optional[int] = None):
        self.size = size
        self.min_size = size or min_size
        self.max_size = size or max_size or self.min_size


class Dataset:
    def __init__(self, logical: L.LogicalPlan):
        self._logical = logical
        self._materialized: Optional[List[Any]] = None  # block refs

    # ------------------------------------------------------ transforms
    def _append(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._logical.with_op(op))

    def map(self, fn: Callable, **opts) -> "Dataset":
        return self._append(L.MapRows(fn=fn, **_map_opts(opts)))

    def filter(self, fn: Callable, **opts) -> "Dataset":
        return self._append(L.Filter(fn=fn, **_map_opts(opts)))

    def flat_map(self, fn: Callable, **opts) -> "Dataset":
        return self._append(L.FlatMap(fn=fn, **_map_opts(opts)))

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[ActorPoolStrategy] = None,
        fn_constructor_args: Tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        num_tpus: Optional[float] = None,
        num_cpus: Optional[float] = None,
        num_gpus: Optional[float] = None,
        concurrency: Optional[Union[int, Tuple[int, int]]] = None,
        zero_copy_batch: bool = False,
        **_ignored,
    ) -> "Dataset":
        resources: Dict[str, float] = {}
        if num_tpus:
            resources["TPU"] = float(num_tpus)
        if num_cpus:
            resources["CPU"] = float(num_cpus)
        if num_gpus:
            resources["GPU"] = float(num_gpus)
        if isinstance(fn, type) and compute is None:
            # class UDFs imply actor compute (reference requires explicit
            # concurrency; we default the pool to `concurrency` or 1)
            compute = ActorPoolStrategy(
                size=concurrency if isinstance(concurrency, int) else None
            )
        return self._append(
            L.MapBatches(
                fn=fn,
                batch_size=batch_size,
                batch_format=batch_format,
                compute=compute,
                fn_constructor_args=tuple(fn_constructor_args),
                fn_constructor_kwargs=dict(fn_constructor_kwargs or {}),
                resources=resources,
                concurrency=concurrency,
                zero_copy_batch=zero_copy_batch,
            )
        )

    def limit(self, n: int) -> "Dataset":
        return self._append(L.Limit(n=n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._append(L.Repartition(num_blocks=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None, num_blocks: Optional[int] = None) -> "Dataset":
        return self._append(L.RandomShuffle(seed=seed, num_blocks=num_blocks))

    def sort(self, key: Union[str, Callable], descending: bool = False) -> "Dataset":
        return self._append(L.Sort(key=key, descending=descending))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def aggregate(self, *aggs: AggregateFn) -> Dict[str, Any]:
        ds = self._append(L.Aggregate(key=None, aggs=list(aggs)))
        rows = list(ds.iter_rows())
        return {k: v for r in rows for k, v in r.items()}

    # scalar aggregates (reference: Dataset.sum/min/max/mean/std —
    # None on an empty dataset, matching the reference's contract)
    def sum(self, on: str):
        return self.aggregate(Sum(on)).get(f"sum({on})")

    def min(self, on: str):
        return self.aggregate(Min(on)).get(f"min({on})")

    def max(self, on: str):
        return self.aggregate(Max(on)).get(f"max({on})")

    def mean(self, on: str):
        return self.aggregate(Mean(on)).get(f"mean({on})")

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(Std(on, ddof=ddof)).get(f"std({on})")

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (reference: Dataset.unique —
        no total order imposed; sorted only when the values allow it)."""
        out: Dict[Any, None] = {}
        for batch in self.select_columns([column]).iter_batches():
            col = np.asarray(batch[column])
            try:
                vals = np.unique(col).tolist()  # C-speed for plain dtypes
            except TypeError:
                vals = col.tolist()  # mixed/unorderable object columns
            for v in vals:
                out[v] = None
        values = list(out)
        try:
            return sorted(values)
        except TypeError:
            return values  # mixed/unorderable types: first-seen order

    def show(self, limit: int = 20) -> None:
        """Print the first rows (reference: Dataset.show)."""
        for row in self.take(limit):
            print(row)

    def union(self, *others: "Dataset") -> "Dataset":
        return self._append(L.Union(others=[o._logical.terminal for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._append(L.Zip(other=other._logical.terminal))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(batch):
            batch[name] = np.asarray(fn(batch))
            return batch

        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return self.map_batches(drop)

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}

        return self.map_batches(select)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(batch):
            return {mapping.get(k, k): v for k, v in batch.items()}

        return self.map_batches(rename)

    # ----------------------------------------------------- consumption
    def _block_refs(self) -> Iterator[Any]:
        if self._materialized is not None:
            return iter(self._materialized)
        executor = StreamingExecutor(build_stages(self._logical))
        self._last_executor = executor
        return executor.execute()

    def materialize(self) -> "Dataset":
        """Execute now; the result caches block refs (reference:
        Dataset.materialize -> MaterializedDataset)."""
        refs = list(self._block_refs())
        ds = Dataset(L.LogicalPlan(L.FromBlocks(blocks=refs)))
        ds._materialized = refs
        ds._last_executor = getattr(self, "_last_executor", None)
        return ds

    def iter_internal_refs(self) -> Iterator[Any]:
        return self._block_refs()

    def iter_rows(self) -> Iterator[Any]:
        import ray_tpu

        for ref in self._block_refs():
            yield from BlockAccessor.for_block(ray_tpu.get(ref)).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        prefetch_batches: Optional[int] = None,
        drop_last: bool = False,
        device_put: Any = None,
    ) -> Iterator[Any]:
        """Stream batches; with ``device_put`` (a jax Device or Sharding)
        batches are staged into device memory ahead of consumption —
        the TPU HBM staging path."""
        from .iterator import iter_batches as _iter

        return _iter(
            self._block_refs(),
            batch_size=batch_size,
            batch_format=batch_format,
            prefetch_batches=(
                prefetch_batches
                if prefetch_batches is not None
                else DataContext.get_current().prefetch_batches
            ),
            drop_last=drop_last,
            device_put=device_put,
        )

    def iter_torch_batches(self, **kwargs) -> Iterator[Any]:
        import torch

        for batch in self.iter_batches(batch_format="numpy", **kwargs):
            yield {k: torch.as_tensor(np.ascontiguousarray(v)) for k, v in batch.items()}

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def take_batch(self, n: int = 20, batch_format: str = "numpy") -> Any:
        import ray_tpu

        blocks, have = [], 0
        for ref in self._block_refs():
            b = ray_tpu.get(ref)
            blocks.append(b)
            have += BlockAccessor.for_block(b).num_rows()
            if have >= n:
                break
        merged = BlockAccessor.concat(blocks)
        acc = BlockAccessor.for_block(merged)
        return BlockAccessor.for_block(acc.slice(0, min(n, acc.num_rows()))).to_batch(batch_format)

    def count(self) -> int:
        import ray_tpu

        count_remote = ray_tpu.remote(
            lambda b: BlockAccessor.for_block(b).num_rows()
        )
        refs = [count_remote.remote(r) for r in self._block_refs()]
        return int(sum(ray_tpu.get(refs)))

    def schema(self) -> Optional[Dict[str, str]]:
        import ray_tpu

        for ref in self._block_refs():
            s = BlockAccessor.for_block(ray_tpu.get(ref)).schema()
            if s:
                return s
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        return list(s.keys()) if s else None

    def num_blocks(self) -> int:
        return sum(1 for _ in self._block_refs())

    def size_bytes(self) -> int:
        import ray_tpu

        return sum(
            BlockAccessor.for_block(ray_tpu.get(r)).size_bytes()
            for r in self._block_refs()
        )

    def to_pandas(self):
        import ray_tpu

        blocks = [ray_tpu.get(r) for r in self._block_refs()]
        merged = BlockAccessor.concat(blocks)
        return BlockAccessor.for_block(merged).to_pandas()

    def to_numpy_refs(self) -> List[Any]:
        return list(self._block_refs())

    # ------------------------------------------------------------ splits
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Materializing split into n datasets (reference: Dataset.split)."""
        import ray_tpu

        refs = list(self._block_refs())
        rows = [
            (r, BlockAccessor.for_block(ray_tpu.get(r)).num_rows()) for r in refs
        ]
        total = sum(c for _, c in rows)
        per = total // n
        out: List[Dataset] = []
        carry: List[Tuple[Any, int]] = list(rows)
        # simple greedy contiguous partition by row count
        targets = [per + (1 if i < total % n else 0) for i in builtins.range(n)]
        if equal:
            targets = [per] * n
        idx = 0
        for t in targets:
            blocks: List[Any] = []
            need = t
            while need > 0 and idx < len(carry):
                ref, cnt = carry[idx]
                if cnt <= need:
                    blocks.append(ref)
                    need -= cnt
                    idx += 1
                else:
                    b = ray_tpu.get(ref)
                    acc = BlockAccessor.for_block(b)
                    blocks.append(ray_tpu.put(acc.slice(0, need)))
                    carry[idx] = (ray_tpu.put(acc.slice(need, cnt)), cnt - need)
                    need = 0
            ds = Dataset(L.LogicalPlan(L.FromBlocks(blocks=blocks)))
            ds._materialized = blocks
            out.append(ds)
        return out

    def streaming_split(self, n: int, *, equal: bool = False, locality_hints=None) -> List["Dataset"]:
        """N coordinated consumers over ONE streaming execution
        (reference: Dataset.streaming_split -> StreamSplitDataIterator
        + its coordinator actor): blocks are claimed pull-based, so a
        slow consumer takes fewer blocks and the dataset still drains
        exactly once per epoch. After all consumers exhaust an epoch,
        the next pull re-runs the plan (per-epoch re-execution, like
        the reference's barrier + restarted executor).

        equal=True needs exact splits, which dynamic claiming cannot
        promise — it materializes and splits statically instead.
        locality_hints are accepted for API parity; the single-hub
        runtime has no per-node block placement to exploit yet.
        """
        if n <= 0:
            raise ValueError("n must be positive")
        if equal:
            return self.split(n, equal=True)
        import ray_tpu

        coord = _SplitCoordinator.remote(
            Dataset(self._logical), n
        )
        return [_StreamSplit(coord, cid, n) for cid in builtins.range(n)]

    def train_test_split(self, test_size: float, *, shuffle: bool = False, seed=None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        mat = ds.materialize()  # execute ONCE; count + slice from the cache
        rows = mat.take_all()
        total = len(rows)
        n_test = int(total * test_size) if isinstance(test_size, float) else test_size
        train, test = rows[: total - n_test], rows[total - n_test :]
        return from_items(train), from_items(test)

    # ------------------------------------------------------------ write
    def write_tfrecords(self, path: str) -> None:
        """One TFRecord shard per block; rows encode as tf.train.Example
        (reference: Dataset.write_tfrecords)."""
        import os

        import ray_tpu

        from ._internal import tfrecords as tfr

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._block_refs()):
            rows = BlockAccessor.for_block(ray_tpu.get(ref)).iter_rows()
            tfr.write_records(
                f"{path}/part-{i:05d}.tfrecords",
                (tfr.encode_example(r) for r in rows),
            )

    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq
        import os

        os.makedirs(path, exist_ok=True)
        import ray_tpu

        for i, ref in enumerate(self._block_refs()):
            table = BlockAccessor.for_block(ray_tpu.get(ref)).to_arrow()
            pq.write_table(table, f"{path}/part-{i:05d}.parquet")

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        import ray_tpu

        for i, ref in enumerate(self._block_refs()):
            df = BlockAccessor.for_block(ray_tpu.get(ref)).to_pandas()
            df.to_csv(f"{path}/part-{i:05d}.csv", index=False)

    def write_json(self, path: str) -> None:
        import json
        import os

        os.makedirs(path, exist_ok=True)
        import ray_tpu

        for i, ref in enumerate(self._block_refs()):
            rows = list(BlockAccessor.for_block(ray_tpu.get(ref)).iter_rows())
            with open(f"{path}/part-{i:05d}.json", "w") as f:
                for r in rows:
                    f.write(json.dumps({k: _json_safe(v) for k, v in r.items()}) + "\n")

    # ------------------------------------------------------------ misc
    def stats(self) -> str:
        """Per-operator execution report (reference: Dataset.stats() /
        data/_internal/stats.py). Wall times are self-times: each
        stage's cumulative pull time minus its upstream's."""
        ops = [op.name for op in self._logical.ops()]
        header = f"Dataset(plan={' -> '.join(ops)})"
        executor = getattr(self, "_last_executor", None)
        stage_stats = getattr(executor, "stage_stats", None) if executor else None
        if not stage_stats:
            return header + "\n  (not executed yet - run materialize() or iterate)"
        lines = [header]
        prev = 0.0
        for s in stage_stats:
            self_time = max(0.0, s["wall_s"] - prev)
            prev = s["wall_s"]
            lines.append(
                f"  {s['name']}: {self_time * 1e3:.1f}ms self, "
                f"{s['blocks']} blocks"
            )
        lines.append(f"  total: {prev * 1e3:.1f}ms")
        return "\n".join(lines)

    def __repr__(self):
        ops = [op.name for op in self._logical.ops()]
        return f"Dataset(plan={' -> '.join(ops)})"

    def _repr_html_(self):
        # Jupyter card (reference: python/ray/widgets dataset repr).
        # Plan-only — no execution triggered by displaying a dataset.
        from ray_tpu import widgets

        ops = [op.name for op in self._logical.ops()]
        return widgets.dataset_html(
            "ray_tpu.data.Dataset", None, [], {"plan": " -> ".join(ops)}
        )


def _json_safe(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def _map_opts(opts: dict) -> dict:
    resources = {}
    if opts.get("num_tpus"):
        resources["TPU"] = float(opts["num_tpus"])
    if opts.get("num_cpus"):
        resources["CPU"] = float(opts["num_cpus"])
    out = {"resources": resources}
    if opts.get("compute"):
        out["compute"] = opts["compute"]
    if opts.get("concurrency") is not None:
        out["concurrency"] = opts["concurrency"]
    return out


class GroupedData:
    """Parity: ray.data.grouped_data.GroupedData."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return self._ds._append(L.Aggregate(key=self._key, aggs=list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: str) -> Dataset:
        return self.aggregate(Std(on))

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy") -> Dataset:
        key = self._key

        def apply(batch):
            acc = BlockAccessor.for_block(BlockAccessor.batch_to_block(batch))
            block = acc.block
            if not isinstance(block, dict):
                raise ValueError("map_groups requires columnar data")
            uniq, inverse = np.unique(block[key], return_inverse=True)
            outs = []
            for g in builtins.range(len(uniq)):
                idx = np.nonzero(inverse == g)[0]
                sub = BlockAccessor.for_block(acc.take(idx)).to_batch(batch_format)
                outs.append(BlockAccessor.batch_to_block(fn(sub)))
            return BlockAccessor.concat(outs)

        # group rows together first via sort, then map whole blocks
        return self._ds.sort(key).map_batches(apply, batch_size=None)


# ---------------------------------------------------------------- read API


def _plan(op: L.LogicalOp) -> Dataset:
    return Dataset(L.LogicalPlan(op))


def range(n: int, *, parallelism: int = -1, override_num_blocks: Optional[int] = None) -> Dataset:
    from .datasource import RangeDatasource

    return read_datasource(
        RangeDatasource(n), parallelism=override_num_blocks or parallelism
    )


def read_datasource(datasource, *, parallelism: int = -1, **_kw) -> Dataset:
    if parallelism is None or parallelism <= 0:
        parallelism = DataContext.get_current().read_op_min_num_blocks
    return _plan(L.Read(datasource=datasource, parallelism=parallelism))


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    from .datasource import ItemsDatasource

    return read_datasource(ItemsDatasource(items), parallelism=parallelism)


def from_numpy(arr, column: str = "data") -> Dataset:
    from .datasource import NumpyDatasource

    arrays = arr if isinstance(arr, list) else [arr]
    return read_datasource(NumpyDatasource(arrays, column), parallelism=len(arrays))


def from_pandas(dfs) -> Dataset:
    dfs = dfs if isinstance(dfs, list) else [dfs]
    import ray_tpu

    refs = [
        ray_tpu.put({c: df[c].to_numpy() for c in df.columns}) for df in dfs
    ]
    return _plan(L.FromBlocks(blocks=refs))


def from_arrow(tables) -> Dataset:
    tables = tables if isinstance(tables, list) else [tables]
    import ray_tpu

    refs = [ray_tpu.put(BlockAccessor.batch_to_block(t)) for t in tables]
    return _plan(L.FromBlocks(blocks=refs))


def read_parquet(paths, *, columns=None, parallelism: int = -1, **_kw) -> Dataset:
    from .datasource import ParquetDatasource

    return read_datasource(ParquetDatasource(paths, columns), parallelism=parallelism)


def read_csv(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    from .datasource import CSVDatasource

    return read_datasource(CSVDatasource(paths), parallelism=parallelism)


def read_json(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    from .datasource import JSONDatasource

    return read_datasource(JSONDatasource(paths), parallelism=parallelism)


def read_text(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    from .datasource import TextDatasource

    return read_datasource(TextDatasource(paths), parallelism=parallelism)


def read_binary_files(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    from .datasource import BinaryDatasource

    return read_datasource(BinaryDatasource(paths), parallelism=parallelism)


def read_tfrecords(paths, *, parallelism: int = -1,
                   verify_crc: bool = False, raw: bool = False,
                   **_kw) -> Dataset:
    """TFRecord files -> one row per record (reference:
    data/_internal/datasource/tfrecords_datasource.py). Records parse
    as tf.train.Example protos into one column per feature (native
    varint+CRC framing and proto codec — no TensorFlow dependency);
    raw=True skips proto decoding and yields {"data": bytes}."""
    from ._internal import tfrecords as tfr
    from .datasource import FileBasedDatasource

    class TFRecordDatasource(FileBasedDatasource):
        def _read_file(self, path: str) -> Block:
            rows = []
            for rec in tfr.read_records(path, verify_crc=verify_crc):
                if raw:
                    rows.append({"data": rec})
                else:
                    rows.append(tfr.decode_example(rec))
            return rows

    return read_datasource(TFRecordDatasource(paths), parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    from .datasource import FileBasedDatasource

    class NpyDatasource(FileBasedDatasource):
        def _read_file(self, path: str) -> Block:
            return {"data": np.load(path)}

    return read_datasource(NpyDatasource(paths), parallelism=parallelism)


def read_webdataset(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    """WebDataset tar shards -> one row per sample key; each extension
    becomes a column of raw bytes, with .cls/.txt/.json decoded
    (reference: data/datasource/webdataset_datasource.py; implemented
    on stdlib tarfile — one read task per shard)."""
    import json as _json
    import tarfile

    from .datasource import FileBasedDatasource

    class WebDatasetDatasource(FileBasedDatasource):
        def _read_file(self, path: str) -> Block:
            samples: Dict[str, dict] = {}
            order: List[str] = []
            with tarfile.open(path) as tf:
                for member in tf:
                    if not member.isfile():
                        continue
                    # WebDataset convention: key = path up to the FIRST
                    # dot of the BASENAME (dots in directories are part
                    # of the key, not the extension)
                    dirname, _, fname = member.name.rpartition("/")
                    stem, _, ext = fname.partition(".")
                    base = f"{dirname}/{stem}" if dirname else stem
                    raw = tf.extractfile(member).read()
                    if base not in samples:
                        samples[base] = {"__key__": base}
                        order.append(base)
                    if ext in ("cls", "index"):
                        samples[base][ext] = int(raw)
                    elif ext in ("txt", "text"):
                        samples[base][ext] = raw.decode()
                    elif ext == "json":
                        samples[base][ext] = _json.loads(raw)
                    else:
                        samples[base][ext] = raw
            return [samples[k] for k in order]

    return read_datasource(WebDatasetDatasource(paths), parallelism=parallelism)


def read_images(
    paths,
    *,
    size: Optional[Tuple[int, int]] = None,
    mode: Optional[str] = None,
    include_paths: bool = False,
    parallelism: int = -1,
    **_kw,
) -> Dataset:
    """Decode image files into an "image" column of HWC uint8 arrays
    (reference: data/datasource/image_datasource.py read_images — size/
    mode resize+convert on read so downstream batches are rectangular)."""
    from .datasource import FileBasedDatasource

    class ImageDatasource(FileBasedDatasource):
        def _read_file(self, path: str) -> Block:
            from PIL import Image

            with Image.open(path) as im:
                if mode is not None:
                    im = im.convert(mode)
                if size is not None:
                    im = im.resize((size[1], size[0]))  # PIL takes (W, H)
                arr = np.asarray(im)
            row = {"image": arr}
            if include_paths:
                row["path"] = path
            return [row]

    return read_datasource(ImageDatasource(paths), parallelism=parallelism)


# ------------------------------------------------------ streaming_split
class _SplitCoordinatorImpl:
    """Owns one streaming execution; consumers claim blocks pull-based.

    Reference: data/_internal/execution/streaming_executor's split
    coordinator actor (StreamSplitDataIterator): exactly-once block
    delivery per epoch, epoch barrier before re-execution.
    """

    def __init__(self, ds, n: int):
        self._ds = ds
        self._n = n
        self._it = None
        self._exhausted: set = set()

    def next_block(self, consumer_id: int):
        """One block ref, "__wait__" (epoch barrier), or None (epoch
        end for this consumer)."""
        if consumer_id in self._exhausted:
            # consumer is into its next epoch; wait for the stragglers,
            # then restart the plan
            if len(self._exhausted) < self._n:
                return "__wait__"
            self._it = None
            self._exhausted = set()
        if self._it is None:
            self._it = iter(self._ds.iter_internal_refs())
        try:
            return next(self._it)
        except StopIteration:
            self._exhausted.add(consumer_id)
            return None


_split_coordinator_cls = None


class _SplitCoordinator:
    """Lazy ray_tpu.remote wrapper (dataset.py imports before init)."""

    @staticmethod
    def remote(ds, n: int):
        global _split_coordinator_cls
        import ray_tpu

        if _split_coordinator_cls is None:
            _split_coordinator_cls = ray_tpu.remote(_SplitCoordinatorImpl)
        return _split_coordinator_cls.remote(ds, n)


class _StreamSplit(Dataset):
    """One consumer's view of a coordinated streaming split.

    Consumption-only (like the reference's StreamSplitDataIterator,
    which is a DataIterator, not a Dataset): apply transforms BEFORE
    streaming_split — blocks here come from the shared coordinator, so
    a per-consumer logical plan would be silently empty.
    """

    BARRIER_TIMEOUT_S = 600.0

    def __init__(self, coord, consumer_id: int, n: int):
        super().__init__(L.LogicalPlan(L.FromBlocks(blocks=[])))
        self._coord = coord
        self._cid = consumer_id
        self._n = n

    def _append(self, op):
        raise TypeError(
            "streaming_split outputs are consume-only iterators "
            "(reference: StreamSplitDataIterator); apply transforms to "
            "the dataset BEFORE streaming_split()"
        )

    def _block_refs(self):
        import time

        import ray_tpu

        waited = 0.0
        while True:
            # per-block protocol round-trip: blocks are consumed
            # strictly in order, there is nothing to batch
            out = ray_tpu.get(self._coord.next_block.remote(self._cid))  # graftlint: disable=GL004
            if isinstance(out, str) and out == "__wait__":
                # epoch barrier: siblings must exhaust the epoch too
                if waited >= self.BARRIER_TIMEOUT_S:
                    raise RuntimeError(
                        f"streaming_split epoch barrier timed out: all "
                        f"{self._n} consumers must iterate every epoch"
                    )
                time.sleep(0.02)
                waited += 0.02
                continue
            waited = 0.0
            if out is None:
                return
            yield out

    def __reduce__(self):
        return (_StreamSplit, (self._coord, self._cid, self._n))
