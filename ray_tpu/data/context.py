"""DataContext: per-driver execution configuration singleton.

Parity: python/ray/data/context.py (DataContext.get_current, target
block sizes, execution caps, use_push_based_shuffle :255).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import ClassVar, Optional


@dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # max concurrently-running block tasks per operator (the streaming
    # executor's admission cap; reference analogue: ResourceManager +
    # concurrency-cap backpressure policy)
    max_tasks_in_flight: int = 8
    read_op_min_num_blocks: int = 8
    use_push_based_shuffle: bool = True
    # hash-partition count for groupby/aggregate (was hard-capped at 8 —
    # r1 Weak finding; reference sizes this from cluster parallelism)
    shuffle_partitions: int = 64
    # stage into device memory in iter_batches when a device is requested
    prefetch_batches: int = 2
    eager_free: bool = True

    _lock: ClassVar[threading.Lock] = threading.Lock()
    _current: ClassVar[Optional["DataContext"]] = None

    @staticmethod
    def get_current() -> "DataContext":
        with DataContext._lock:
            if DataContext._current is None:
                DataContext._current = DataContext()
            return DataContext._current
