"""ray_tpu.data — distributed datasets for TPU pipelines.

Parity: python/ray/data/ in the reference (Dataset, read_api,
aggregate, ActorPoolStrategy, DataContext). Columnar-numpy blocks,
lazy logical plans, a streaming task/actor-pool executor, and HBM
batch staging. See dataset.py for the surface.
"""

from .aggregate import AbsMax, AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import Block, BlockAccessor, BlockMetadata
from .context import DataContext
from .dataset import (
    ActorPoolStrategy,
    Dataset,
    GroupedData,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
    read_tfrecords,
    read_webdataset,
)
from .datasource import Datasource, ReadTask
from . import preprocessors

__all__ = [
    "AbsMax", "ActorPoolStrategy", "AggregateFn", "Block", "BlockAccessor",
    "BlockMetadata", "Count", "DataContext", "Dataset", "Datasource",
    "GroupedData", "Max", "Mean", "Min", "ReadTask", "Std", "Sum",
    "from_arrow", "from_items", "from_numpy", "from_pandas", "range",
    "read_binary_files", "read_csv", "read_datasource", "read_images",
    "read_json", "read_numpy", "read_parquet", "read_text", "read_tfrecords",
    "read_webdataset",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("data")
