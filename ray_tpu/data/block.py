"""Blocks: the unit of distributed data.

Parity: python/ray/data/block.py in the reference (Block = Arrow/pandas
table; BlockAccessor; BlockMetadata). TPU-native choice: the canonical
in-memory format is a **dict of numpy column arrays** — the exact thing
`jax.device_put` stages into HBM with zero conversion — with a row-list
fallback for arbitrary Python objects. Arrow/pandas are import/export
formats, not the hot path.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

# A Block is either a columnar batch {col -> ndarray} or a list of rows.
Block = Union[Dict[str, np.ndarray], List[Any]]


@dataclass
class BlockMetadata:
    """Parity: data/block.py BlockMetadata (num_rows, size_bytes,
    schema, input_files, exec_stats)."""

    num_rows: Optional[int] = None
    size_bytes: Optional[int] = None
    schema: Optional[Dict[str, str]] = None
    input_files: List[str] = field(default_factory=list)


def _rows_to_columns(rows: List[Any]) -> Optional[Dict[str, np.ndarray]]:
    """Try to columnarize a list of dict-rows; None if heterogeneous."""
    if not rows or not all(isinstance(r, dict) for r in rows):
        return None
    keys = list(rows[0].keys())
    if not all(list(r.keys()) == keys for r in rows):
        return None
    try:
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    except Exception:
        return None


class BlockAccessor:
    """Uniform view over both block representations
    (parity: data/block.py BlockAccessor.for_block)."""

    def __init__(self, block: Block):
        self._block = block
        self._is_columnar = isinstance(block, dict)

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """Normalize a UDF return (dict/ndarray/pandas/arrow/list) into a Block."""
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        if isinstance(batch, np.ndarray):
            return {"data": batch}
        if batch.__class__.__module__.startswith("pandas"):
            return {c: batch[c].to_numpy() for c in batch.columns}
        if batch.__class__.__module__.startswith("pyarrow"):
            return {name: col.to_numpy(zero_copy_only=False) for name, col in zip(batch.column_names, batch.columns)}
        if isinstance(batch, list):
            cols = _rows_to_columns(batch)
            return cols if cols is not None else batch
        raise TypeError(f"cannot interpret {type(batch)} as a Block")

    # ------------------------------------------------------------ shape
    @property
    def block(self) -> Block:
        return self._block

    def num_rows(self) -> int:
        if self._is_columnar:
            if not self._block:
                return 0
            return len(next(iter(self._block.values())))
        return len(self._block)

    def size_bytes(self) -> int:
        if self._is_columnar:
            return int(sum(v.nbytes for v in self._block.values()))
        return int(sum(sys.getsizeof(r) for r in self._block))

    def schema(self) -> Optional[Dict[str, str]]:
        if self._is_columnar:
            return {k: str(v.dtype) for k, v in self._block.items()}
        if self._block:
            return {"item": type(self._block[0]).__name__}
        return None

    def metadata(self, input_files: Optional[List[str]] = None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=list(input_files or []),
        )

    # ------------------------------------------------------- row access
    def iter_rows(self) -> Iterator[Any]:
        if self._is_columnar:
            keys = list(self._block.keys())
            for i in range(self.num_rows()):
                yield {k: self._block[k][i] for k in keys}
        else:
            yield from self._block

    def slice(self, start: int, end: int) -> Block:
        if self._is_columnar:
            return {k: v[start:end] for k, v in self._block.items()}
        return self._block[start:end]

    def take(self, indices: np.ndarray) -> Block:
        if self._is_columnar:
            return {k: v[indices] for k, v in self._block.items()}
        return [self._block[i] for i in indices]

    # ------------------------------------------------------ conversions
    def to_batch(self, batch_format: str = "numpy") -> Any:
        if batch_format in ("numpy", "default"):
            if self._is_columnar:
                return dict(self._block)
            cols = _rows_to_columns(self._block)
            return cols if cols is not None else self._block
        if batch_format == "pandas":
            import pandas as pd

            if self._is_columnar:
                return pd.DataFrame({k: list(v) if v.ndim > 1 else v for k, v in self._block.items()})
            return pd.DataFrame(self._block)
        if batch_format == "pyarrow":
            import pyarrow as pa

            if self._is_columnar:
                return pa.table({k: pa.array(list(v)) if v.ndim > 1 else pa.array(v) for k, v in self._block.items()})
            return pa.table({"item": self._block})
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def to_pandas(self):
        return self.to_batch("pandas")

    def to_arrow(self):
        return self.to_batch("pyarrow")

    # --------------------------------------------------------- combine
    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        nonempty = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
        if not nonempty:
            # preserve columnar schema of empty inputs rather than
            # degrading to a row-list (downstream UDFs index columns)
            return blocks[0] if blocks else []
        blocks = nonempty
        if all(isinstance(b, dict) for b in blocks):
            keys = list(blocks[0].keys())
            return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
        rows: List[Any] = []
        for b in blocks:
            rows.extend(BlockAccessor(b).iter_rows())
        return rows

    def sort_indices(self, key: Union[str, Any], descending: bool = False) -> np.ndarray:
        if callable(key):
            vals = np.asarray([key(r) for r in self.iter_rows()])
        elif self._is_columnar:
            vals = self._block[key]
        else:
            vals = np.asarray([r[key] for r in self._block])
        idx = np.argsort(vals, kind="stable")
        return idx[::-1] if descending else idx
