"""Aggregations for groupby/global reduce.

Parity: python/ray/data/aggregate.py (AggregateFn, Count/Sum/Min/Max/
Mean/Std) — implemented as vectorized numpy reductions over columnar
blocks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .block import Block, BlockAccessor


class AggregateFn:
    def __init__(self, on: Optional[str], name: str, reduce_fn: Callable[[np.ndarray], Any]):
        self.on = on
        self.name = name
        self.reduce_fn = reduce_fn

    def output_name(self) -> str:
        return f"{self.name}({self.on})" if self.on else self.name


class Count(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(on, "count", lambda v: int(len(v)))

    def output_name(self) -> str:
        return "count()"


class Sum(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, "sum", lambda v: v.sum())


class Min(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, "min", lambda v: v.min())


class Max(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, "max", lambda v: v.max())


class Mean(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, "mean", lambda v: v.mean())


class Std(AggregateFn):
    def __init__(self, on: str, ddof: int = 1):
        super().__init__(on, "std", lambda v: v.std(ddof=ddof) if len(v) > ddof else 0.0)


class AbsMax(AggregateFn):
    def __init__(self, on: str):
        super().__init__(on, "abs_max", lambda v: np.abs(v).max())


def aggregate_block(block: Block, key: Optional[str], aggs: List[AggregateFn]) -> Block:
    """Group `block` rows by `key` (or globally if None) and apply aggs.
    Returns a columnar block with one row per group."""
    acc = BlockAccessor.for_block(block)
    if acc.num_rows() == 0:
        return {}
    if isinstance(block, dict):
        cols = block
    else:
        cols = BlockAccessor.batch_to_block(list(acc.iter_rows()))
        if not isinstance(cols, dict):
            raise ValueError("aggregate requires dict-style rows or columnar blocks")

    def col_for(agg: AggregateFn, idx: np.ndarray) -> np.ndarray:
        src = cols[agg.on] if agg.on else next(iter(cols.values()))
        return src[idx]

    if key is None:
        idx = np.arange(acc.num_rows())
        return {agg.output_name(): np.asarray([agg.reduce_fn(col_for(agg, idx))]) for agg in aggs}

    keys = cols[key]
    uniq, inverse = np.unique(keys, return_inverse=True)
    out: Dict[str, np.ndarray] = {key: uniq}
    for agg in aggs:
        vals = [agg.reduce_fn(col_for(agg, np.nonzero(inverse == g)[0])) for g in range(len(uniq))]
        out[agg.output_name()] = np.asarray(vals)
    return out
