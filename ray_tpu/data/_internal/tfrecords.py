"""TFRecord framing + a minimal tf.train.Example codec (no TensorFlow).

Parity: python/ray/data/_internal/datasource/tfrecords_datasource.py —
the reference decodes TFRecord files into one column per Example
feature. The wire format is:

    per record: [8B LE length][4B masked crc32c(length)]
                [data][4B masked crc32c(data)]

and `data` is usually a serialized tf.train.Example protobuf:

    Example    { Features features = 1; }
    Features   { map<string, Feature> feature = 1; }
    Feature    { oneof { BytesList=1; FloatList=2; Int64List=3 } }
    BytesList  { repeated bytes value = 1; }
    FloatList  { repeated float value = 1 [packed]; }
    Int64List  { repeated int64 value = 1 [packed]; }

Both directions are implemented directly against that fixed schema —
a handful of varint/tag cases — because protobuf/tensorflow are not
runtime dependencies of this framework.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

# ---------------------------------------------------------------- crc32c
# Castagnoli polynomial (reversed): the CRC TFRecord uses, NOT zlib's.
_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    c = 0xFFFFFFFF
    for b in data:
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return ((c >> 15 | c << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- framing
def read_records(path: str, *, verify_crc: bool = False) -> Iterator[bytes]:
    """Yield raw record payloads. Length CRCs are always checked (they
    guard the framing); data CRCs only with verify_crc (linear cost)."""
    with open(path, "rb") as f:
        while True:
            head = f.read(12)
            if not head:
                return
            if len(head) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,) = struct.unpack("<Q", head[:8])
            (len_crc,) = struct.unpack("<I", head[8:12])
            if _masked_crc(head[:8]) != len_crc:
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            foot = f.read(4)
            if len(data) < length or len(foot) < 4:
                raise ValueError(f"truncated TFRecord payload in {path}")
            if verify_crc:
                (data_crc,) = struct.unpack("<I", foot)
                if _masked_crc(data) != data_crc:
                    raise ValueError(f"corrupt TFRecord data crc in {path}")
            yield data


def write_records(path: str, records: Iterator[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            head = struct.pack("<Q", len(rec))
            f.write(head)
            f.write(struct.pack("<I", _masked_crc(head)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))


# -------------------------------------------------------- proto helpers
def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message buffer.
    value is bytes for length-delimited, int for varint, raw for
    fixed32/64."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            v, pos = _read_varint(buf, pos)
        elif wt == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:  # fixed32
            v = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:  # fixed64
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _decode_feature(buf: bytes) -> Any:
    """Feature -> python value (singletons unwrap like the reference)."""
    for field, wt, v in _iter_fields(buf):
        if field == 1:  # BytesList
            vals = [bv for f2, _, bv in _iter_fields(v) if f2 == 1]
            return vals[0] if len(vals) == 1 else vals
        if field == 2:  # FloatList (packed or repeated fixed32)
            vals: List[float] = []
            for f2, wt2, fv in _iter_fields(v):
                if f2 != 1:
                    continue
                if wt2 == 2:  # packed
                    vals.extend(
                        struct.unpack(f"<{len(fv) // 4}f", fv)
                    )
                else:
                    vals.append(struct.unpack("<f", fv)[0])
            return vals[0] if len(vals) == 1 else vals
        if field == 3:  # Int64List (packed or repeated varint)
            vals = []
            for f2, wt2, iv in _iter_fields(v):
                if f2 != 1:
                    continue
                if wt2 == 2:  # packed
                    pos = 0
                    while pos < len(iv):
                        x, pos = _read_varint(iv, pos)
                        vals.append(_to_signed64(x))
                else:
                    vals.append(_to_signed64(iv))
            return vals[0] if len(vals) == 1 else vals
    return None


def _to_signed64(x: int) -> int:
    return x - (1 << 64) if x >= (1 << 63) else x


def decode_example(record: bytes) -> Dict[str, Any]:
    """Serialized tf.train.Example -> {feature_name: value}."""
    row: Dict[str, Any] = {}
    for field, _, v in _iter_fields(record):
        if field != 1:  # Example.features
            continue
        for f2, _, entry in _iter_fields(v):
            if f2 != 1:  # Features.feature map entry
                continue
            key = None
            feat = None
            for f3, _, ev in _iter_fields(entry):
                if f3 == 1:
                    key = ev.decode()
                elif f3 == 2:
                    feat = ev
            if key is not None:
                row[key] = _decode_feature(feat) if feat is not None else None
    return row


def _ld(out: bytearray, field: int, payload: bytes) -> None:
    _write_varint(out, field << 3 | 2)
    _write_varint(out, len(payload))
    out += payload


def encode_example(row: Dict[str, Any]) -> bytes:
    """{name: value} -> serialized tf.train.Example. bytes/str ->
    BytesList, float -> FloatList, int/bool -> Int64List; lists of the
    same follow their element type."""
    features = bytearray()
    for key, value in row.items():
        vals = value if isinstance(value, (list, tuple)) else [value]
        try:
            import numpy as np

            if isinstance(value, np.ndarray):
                vals = value.tolist()
            vals = [
                v.item() if isinstance(v, np.generic) else v for v in vals
            ]
        except ImportError:  # pragma: no cover
            pass
        kind = bytearray()
        if all(isinstance(v, (bytes, str)) for v in vals):
            blist = bytearray()
            for v in vals:
                _ld(blist, 1, v.encode() if isinstance(v, str) else v)
            _ld(kind, 1, bytes(blist))  # Feature.bytes_list
        elif all(isinstance(v, bool) or isinstance(v, int) for v in vals):
            packed = bytearray()
            for v in vals:
                _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
            ilist = bytearray()
            _ld(ilist, 1, bytes(packed))
            _ld(kind, 3, bytes(ilist))  # Feature.int64_list
        elif all(isinstance(v, (int, float)) for v in vals):
            packed = b"".join(struct.pack("<f", float(v)) for v in vals)
            flist = bytearray()
            _ld(flist, 1, packed)
            _ld(kind, 2, bytes(flist))  # Feature.float_list
        else:
            raise TypeError(
                f"feature {key!r} has unsupported value type for "
                f"tf.train.Example: {type(vals[0]).__name__}"
            )
        entry = bytearray()
        _ld(entry, 1, key.encode())
        _ld(entry, 2, bytes(kind))
        # map<string, Feature> == repeated field-1 map-entry messages
        _ld(features, 1, bytes(entry))
    example = bytearray()
    _ld(example, 1, bytes(features))
    return bytes(example)
