"""Logical plan: lazy operator DAG + rule-based fusion.

Parity: python/ray/data/_internal/logical/ (LogicalPlan, operators,
optimizers.py fusion rules) collapsed to the ops that matter. The key
optimization is the same one the reference's OperatorFusionRule does:
adjacent one-to-one transforms (map/filter/flat_map/map_batches with
task compute) fuse into ONE task chain so blocks cross the object
store once per fused group, not once per op.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ..datasource import Datasource


@dataclass
class LogicalOp:
    input: Optional["LogicalOp"] = None

    @property
    def name(self) -> str:
        return type(self).__name__


@dataclass
class Read(LogicalOp):
    datasource: Optional[Datasource] = None
    parallelism: int = -1


@dataclass
class FromBlocks(LogicalOp):
    """Already-materialized blocks (from_pandas/from_numpy refs)."""

    blocks: List[Any] = field(default_factory=list)  # ObjectRefs


@dataclass
class OneToOne(LogicalOp):
    """Base for per-block transforms; carries compute config."""

    fn: Optional[Callable] = None
    compute: Optional[Any] = None  # None=tasks, ActorPoolStrategy=actors
    fn_constructor_args: Tuple = ()
    fn_constructor_kwargs: Dict[str, Any] = field(default_factory=dict)
    resources: Dict[str, float] = field(default_factory=dict)
    concurrency: Optional[Union[int, Tuple[int, int]]] = None


@dataclass
class MapRows(OneToOne):
    pass


@dataclass
class Filter(OneToOne):
    pass


@dataclass
class FlatMap(OneToOne):
    pass


@dataclass
class MapBatches(OneToOne):
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    zero_copy_batch: bool = False


@dataclass
class Limit(LogicalOp):
    n: int = 0


@dataclass
class Repartition(LogicalOp):
    num_blocks: int = 1


@dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None
    num_blocks: Optional[int] = None


@dataclass
class Sort(LogicalOp):
    key: Optional[Union[str, Callable]] = None
    descending: bool = False


@dataclass
class Aggregate(LogicalOp):
    key: Optional[str] = None
    aggs: List[Any] = field(default_factory=list)


@dataclass
class Union(LogicalOp):
    others: List["LogicalOp"] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    other: Optional["LogicalOp"] = None


class LogicalPlan:
    def __init__(self, terminal: LogicalOp):
        self.terminal = terminal

    def ops(self) -> List[LogicalOp]:
        """Linear chain root..terminal (branches hang off Union/Zip)."""
        chain: List[LogicalOp] = []
        op: Optional[LogicalOp] = self.terminal
        while op is not None:
            chain.append(op)
            op = op.input
        return list(reversed(chain))

    def with_op(self, op: LogicalOp) -> "LogicalPlan":
        op.input = self.terminal
        return LogicalPlan(op)
