"""Streaming executor: runs a logical plan as a pipeline of block tasks.

Parity: python/ray/data/_internal/execution/streaming_executor.py:52 —
a control loop that dispatches `ray.remote` block tasks per operator,
streams finished blocks downstream, and caps in-flight work (the
ResourceManager/backpressure role is played by `max_tasks_in_flight`).
One-to-one stages pipeline (a block flows to the next stage while its
siblings are still being produced); all-to-all stages (shuffle, sort,
aggregate, repartition) are barriers, implemented as 2-stage
partition/merge task graphs (the push-based shuffle shape,
data/_internal/planner/exchange/push_based_shuffle_task_scheduler.py).

Actor-pool compute (reference: ActorPoolMapOperator) pins stateful/
device UDFs to a pool of actors — the `num_tpus` batch-inference path:
each pool actor owns chips for its lifetime and the UDF keeps jitted
programs warm across batches.
"""

from __future__ import annotations

import random
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..block import Block, BlockAccessor
from ..context import DataContext
from . import plan as L

# ---------------------------------------------------------------- UDF glue


def _apply_one(op_kind: str, fn: Callable, spec: dict, block: Block) -> List[Block]:
    """Apply one logical op to one block, returning output blocks."""
    acc = BlockAccessor.for_block(block)
    if acc.num_rows() == 0:
        return []  # drop empty blocks; never invoke UDFs on them
    if op_kind == "map_rows":
        return [BlockAccessor.batch_to_block([fn(r) for r in acc.iter_rows()])]
    if op_kind == "filter":
        rows = [r for r in acc.iter_rows() if fn(r)]
        return [BlockAccessor.batch_to_block(rows)] if rows else []
    if op_kind == "flat_map":
        rows: List[Any] = []
        for r in acc.iter_rows():
            rows.extend(fn(r))
        return [BlockAccessor.batch_to_block(rows)] if rows else []
    if op_kind == "map_batches":
        bs = spec.get("batch_size")
        fmt = spec.get("batch_format", "numpy")
        n = acc.num_rows()
        out: List[Block] = []
        step = bs or n
        for lo in range(0, n, step):
            sub = BlockAccessor.for_block(acc.slice(lo, min(lo + step, n)))
            res = fn(sub.to_batch(fmt))
            out.append(BlockAccessor.batch_to_block(res))
        return out
    raise ValueError(f"unknown op kind {op_kind}")


def _apply_chain(chain: List[Tuple[str, Callable, dict]], block: Block) -> List[Block]:
    blocks = [block]
    for kind, fn, spec in chain:
        nxt: List[Block] = []
        for b in blocks:
            nxt.extend(_apply_one(kind, fn, spec, b))
        blocks = nxt
    return blocks


def _publish(blocks: List[Block]) -> List[Any]:
    """Worker-side: put each output block into the object store and
    return just the refs — blocks never round-trip through the driver
    (the reference's tasks likewise seal blocks into plasma and ship
    RefBundles of metadata, §3.5 step 3)."""
    import ray_tpu

    return [ray_tpu.put(b) for b in blocks]


def _run_read_task(read_fn: Callable, chain: List[Tuple[str, Callable, dict]]):
    """Worker-side: run a ReadTask then the fused transform chain."""
    out: List[Block] = []
    for block in read_fn():
        out.extend(_apply_chain(chain, block))
    return _publish(out)


def _run_chain_task(chain: List[Tuple[str, Callable, dict]], block: Block):
    return _publish(_apply_chain(chain, block))


class _ChainActor:
    """Actor-pool UDF host (reference: ActorPoolMapOperator worker).
    Instantiates callable-class UDFs once; TPU chips assigned to this
    actor stay pinned so jitted state persists across batches."""

    def __init__(self, chain_spec: List[Tuple[str, Any, dict, tuple, dict]]):
        self.chain: List[Tuple[str, Callable, dict]] = []
        for kind, fn, spec, ctor_args, ctor_kwargs in chain_spec:
            if isinstance(fn, type):
                fn = fn(*ctor_args, **ctor_kwargs)
            self.chain.append((kind, fn, spec))

    def run(self, block: Block) -> List[Any]:
        return _publish(_apply_chain(self.chain, block))


# ------------------------------------------------------------ physical plan


class _Stage:
    kind = "abstract"

    def __init__(self, name: str):
        self.name = name


class _InputStage(_Stage):
    kind = "input"

    def __init__(self, refs: List[Any]):
        super().__init__("Input")
        self.refs = refs


class _ReadStage(_Stage):
    kind = "read"

    def __init__(self, read_tasks, chain, name):
        super().__init__(name)
        self.read_tasks = read_tasks
        self.chain = chain


class _MapStage(_Stage):
    kind = "map"

    def __init__(self, chain, name, compute=None, resources=None, concurrency=None):
        super().__init__(name)
        self.chain = chain  # [(kind, fn, spec, ctor_args, ctor_kwargs)]
        self.compute = compute
        self.resources = dict(resources or {})
        self.concurrency = concurrency


class _AllToAllStage(_Stage):
    kind = "all_to_all"

    def __init__(self, op: L.LogicalOp, name: str):
        super().__init__(name)
        self.op = op


class _LimitStage(_Stage):
    kind = "limit"

    def __init__(self, n: int):
        super().__init__(f"Limit[{n}]")
        self.n = n


def _op_to_chain_entry(op: L.OneToOne):
    kind = {
        L.MapRows: "map_rows",
        L.Filter: "filter",
        L.FlatMap: "flat_map",
        L.MapBatches: "map_batches",
    }[type(op)]
    spec = {}
    if isinstance(op, L.MapBatches):
        spec = {"batch_size": op.batch_size, "batch_format": op.batch_format}
    return (
        kind,
        op.fn,
        spec,
        tuple(op.fn_constructor_args),
        dict(op.fn_constructor_kwargs),
    )


def build_stages(logical: L.LogicalPlan) -> List[_Stage]:
    """Lower + fuse: adjacent task-compute OneToOne ops merge into one
    _MapStage; a leading fused chain merges into the read stage."""
    stages: List[_Stage] = []
    pending_chain: List[tuple] = []
    pending_meta: List[str] = []
    pending_compute = None
    pending_resources: Dict[str, float] = {}
    pending_concurrency = None

    def flush():
        nonlocal pending_chain, pending_compute, pending_resources
        nonlocal pending_concurrency, pending_meta
        if not pending_chain:
            return
        name = "->".join(pending_meta)
        if (
            stages
            and isinstance(stages[-1], _ReadStage)
            and pending_compute is None
            and not pending_resources
        ):
            stages[-1].chain = stages[-1].chain + list(pending_chain)
            stages[-1].name += "->" + name
        else:
            stages.append(
                _MapStage(
                    list(pending_chain),
                    name,
                    compute=pending_compute,
                    resources=pending_resources,
                    concurrency=pending_concurrency,
                )
            )
        pending_chain = []
        pending_meta = []
        pending_compute = None
        pending_resources = {}
        pending_concurrency = None

    for op in logical.ops():
        if isinstance(op, L.Read):
            stages.append(
                _ReadStage(
                    op.datasource.get_read_tasks(op.parallelism),
                    [],
                    f"Read{op.datasource.get_name()}",
                )
            )
        elif isinstance(op, L.FromBlocks):
            stages.append(_InputStage(op.blocks))
        elif isinstance(op, L.OneToOne):
            uses_actors = op.compute is not None
            has_res = bool(op.resources)
            if pending_chain and (uses_actors or has_res or pending_compute is not None):
                flush()
            pending_chain.append(_op_to_chain_entry(op))
            pending_meta.append(op.name)
            if uses_actors:
                pending_compute = op.compute
            if has_res:
                pending_resources = dict(op.resources)
            if op.concurrency is not None:
                pending_concurrency = op.concurrency
            if uses_actors or has_res:
                flush()
        elif isinstance(op, L.Limit):
            flush()
            stages.append(_LimitStage(op.n))
        elif isinstance(op, (L.Repartition, L.RandomShuffle, L.Sort, L.Aggregate, L.Union, L.Zip)):
            flush()
            stages.append(_AllToAllStage(op, op.name))
        else:
            raise NotImplementedError(f"op {op.name}")
    flush()
    return stages


# ---------------------------------------------------------------- executor


class StreamingExecutor:
    """Pull-driven pipeline. `execute()` yields final block refs as they
    become available."""

    def __init__(self, stages: List[_Stage]):
        self.stages = stages
        self.ctx = DataContext.get_current()

    # -- public -------------------------------------------------------
    def execute(self) -> Iterator[Any]:
        """Yield ObjectRefs of final blocks (each ref -> List[Block]-free
        single Block). Per-stage wall times and block counts accumulate
        in ``self.stage_stats`` (reference: data/_internal/stats.py
        per-operator DatasetStats behind ds.stats())."""
        import time as _time

        self.stage_stats: List[dict] = []
        stream: Iterator[Any] = iter(())
        for stage in self.stages:
            if stage.kind == "input":
                stream = iter(stage.refs)
            elif stage.kind == "read":
                stream = self._run_read(stage)
            elif stage.kind == "map":
                stream = self._run_map(stage, stream)
            elif stage.kind == "limit":
                stream = self._run_limit(stage, stream)
            elif stage.kind == "all_to_all":
                stream = self._run_all_to_all(stage, stream)
            if stage.kind != "input":
                stat = {"name": stage.name, "wall_s": 0.0, "blocks": 0}
                self.stage_stats.append(stat)
                stream = self._timed(stream, stat, _time)
        return self._publish_stats_on_drain(stream)

    def _publish_stats_on_drain(self, stream: Iterator[Any]) -> Iterator[Any]:
        """When the pipeline drains, snapshot per-op stats into the
        cluster KV so the dashboard's data view can render them
        (reference: the dashboard's data section reads
        DatasetStats via the stats actor)."""
        yield from stream
        try:
            import json as _json
            import time as _time

            from ray_tpu._private import worker

            client = worker._client
            if client is None:
                return
            snap = _json.dumps({
                "finished_at": _time.time(),
                "stages": self.stage_stats,
            }).encode()
            client.kv_put(
                f"__data_stats__{_time.time():.6f}".encode(), snap,
                overwrite=True,
            )
            # bound the ring: keep the newest 50 snapshots
            keys = sorted(client.kv_keys(b"__data_stats__"))
            for k in keys[:-50]:
                client.kv_del(k)
        except Exception:
            pass  # stats publishing must never fail a data job

    @staticmethod
    def _timed(stream: Iterator[Any], stat: dict, _time) -> Iterator[Any]:
        """Cumulative time spent pulling through this stage's iterator
        (includes upstream; ds.stats() reports the self-time deltas)."""
        while True:
            t0 = _time.perf_counter()
            try:
                item = next(stream)
            except StopIteration:
                stat["wall_s"] += _time.perf_counter() - t0
                return
            stat["wall_s"] += _time.perf_counter() - t0
            stat["blocks"] += 1
            yield item

    # -- helpers ------------------------------------------------------
    def _ray(self):
        import ray_tpu

        return ray_tpu

    def _flatten_refs(self, list_ref) -> List[Any]:
        """A task returned List[ObjectRef] (blocks already published by
        the worker); only the small ref list crosses to the driver."""
        ray = self._ray()
        return list(ray.get(list_ref))

    def _run_read(self, stage: _ReadStage) -> Iterator[Any]:
        ray = self._ray()
        remote = ray.remote(_run_read_task)
        plain_chain = [(k, f, s) for (k, f, s, _a, _kw) in stage.chain]
        pending = deque(stage.read_tasks)
        in_flight: deque = deque()  # submission order == output order
        cap = self.ctx.max_tasks_in_flight
        while pending or in_flight:
            batch = []
            while pending and len(in_flight) + len(batch) < cap:
                rt = pending.popleft()
                batch.append((rt.read_fn, plain_chain))
            if batch:
                # one SUBMIT_TASKS frame per window refill, not one
                # frame per read task
                in_flight.extend(remote.map(batch))
            yield from self._flatten_refs(in_flight.popleft())

    def _run_map(self, stage: _MapStage, upstream: Iterator[Any]) -> Iterator[Any]:
        if stage.compute is not None:
            yield from self._run_actor_map(stage, upstream)
            return
        ray = self._ray()
        remote = ray.remote(_run_chain_task)
        if stage.resources:
            opts = {}
            if "TPU" in stage.resources:
                opts["num_tpus"] = stage.resources["TPU"]
            if "CPU" in stage.resources:
                opts["num_cpus"] = stage.resources["CPU"]
            rest = {k: v for k, v in stage.resources.items() if k not in ("TPU", "CPU")}
            if rest:
                opts["resources"] = rest
            remote = remote.options(**opts)
        plain_chain = [(k, f, s) for (k, f, s, _a, _kw) in stage.chain]
        in_flight: deque = deque()  # submission order == output order
        cap = self.ctx.max_tasks_in_flight
        upstream_done = False
        up = upstream
        while not upstream_done or in_flight:
            batch = []
            while not upstream_done and len(in_flight) + len(batch) < cap:
                try:
                    block_ref = next(up)
                except StopIteration:
                    upstream_done = True
                    break
                batch.append((plain_chain, block_ref))
            if batch:
                # whole window refill rides one SUBMIT_TASKS frame
                in_flight.extend(remote.map(batch))
            if not in_flight:
                continue
            yield from self._flatten_refs(in_flight.popleft())

    def _run_actor_map(self, stage: _MapStage, upstream: Iterator[Any]) -> Iterator[Any]:
        ray = self._ray()
        compute = stage.compute
        size = getattr(compute, "size", None) or getattr(compute, "min_size", 1)
        if isinstance(stage.concurrency, int):
            size = stage.concurrency
        elif isinstance(stage.concurrency, tuple):
            size = stage.concurrency[0]
        actor_cls = ray.remote(_ChainActor)
        opts: Dict[str, Any] = {"num_cpus": stage.resources.get("CPU", 1)}
        if stage.resources.get("TPU"):
            opts["num_tpus"] = stage.resources["TPU"]
        pool = [
            actor_cls.options(**opts).remote(stage.chain) for _ in range(size)
        ]
        try:
            idle = deque(pool)
            busy: Dict[Any, Any] = {}  # ref -> actor
            submitted: deque = deque()  # output order
            completed = set()
            upstream_done = False
            up = upstream
            while not upstream_done or busy or submitted:
                while not upstream_done and idle:
                    try:
                        block_ref = next(up)
                    except StopIteration:
                        upstream_done = True
                        break
                    actor = idle.popleft()
                    ref = actor.run.remote(block_ref)
                    busy[ref] = actor
                    submitted.append(ref)
                if busy:
                    ready, _ = ray.wait(list(busy.keys()), num_returns=1)
                    for r in ready:
                        idle.append(busy.pop(r))
                        completed.add(r)
                # emit in submission order as soon as the head is done
                while submitted and submitted[0] in completed:
                    completed.discard(submitted[0])
                    yield from self._flatten_refs(submitted.popleft())
        finally:
            for a in pool:
                try:
                    ray.kill(a)
                except Exception:
                    pass

    def _run_limit(self, stage: _LimitStage, upstream: Iterator[Any]) -> Iterator[Any]:
        ray = self._ray()
        remaining = stage.n
        for ref in upstream:
            if remaining <= 0:
                break
            block = ray.get(ref)
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            if n <= remaining:
                remaining -= n
                yield ref
            else:
                yield ray.put(acc.slice(0, remaining))
                remaining = 0

    # -- all-to-all ----------------------------------------------------
    def _run_all_to_all(self, stage: _AllToAllStage, upstream: Iterator[Any]) -> Iterator[Any]:
        op = stage.op
        refs = list(upstream)  # barrier
        if isinstance(op, L.Repartition):
            yield from self._repartition(refs, op.num_blocks)
        elif isinstance(op, L.RandomShuffle):
            yield from self._random_shuffle(refs, op.seed, op.num_blocks)
        elif isinstance(op, L.Sort):
            yield from self._sort(refs, op.key, op.descending)
        elif isinstance(op, L.Aggregate):
            yield from self._aggregate(refs, op.key, op.aggs)
        elif isinstance(op, L.Union):
            yield from refs
            for other in op.others:
                other_stages = build_stages(L.LogicalPlan(other))
                yield from StreamingExecutor(other_stages).execute()
        elif isinstance(op, L.Zip):
            yield from self._zip(refs, op.other)
        else:
            raise NotImplementedError(op.name)

    def _exchange_parts(
        self, refs: List[Any], submit_split: Callable[[Any], List[Any]], k: int
    ) -> List[List[Any]]:
        """Map phase of a 2-stage exchange -> per-partition piece lists.

        With ``DataContext.use_push_based_shuffle`` (default), map
        outputs are consumed in rounds of ~sqrt(M): each round's k
        pieces are partially concatenated as soon as that round's maps
        are submitted, so partial merges overlap the remaining maps and
        the final per-partition merge fans in O(sqrt(M)) refs instead of
        M (reference: push_based_shuffle_task_scheduler.py:112,400 —
        pipelined map/merge rounds). Pull-based fallback keeps one piece
        per map."""
        parts: List[List[Any]] = [[] for _ in range(k)]
        if k == 1:
            parts[0] = list(refs)
            return parts
        push = self.ctx.use_push_based_shuffle and len(refs) > 3
        if not push:
            for ref in refs:
                for i, piece in enumerate(submit_split(ref)):
                    parts[i].append(piece)
            return parts
        ray = self._ray()
        concat = ray.remote(lambda *bs: BlockAccessor.concat(list(bs)))
        round_size = max(2, int(len(refs) ** 0.5))
        pending: List[List[Any]] = []

        def flush_round():
            for i in range(k):
                pieces = [out[i] for out in pending]
                parts[i].append(
                    concat.remote(*pieces) if len(pieces) > 1 else pieces[0]
                )

        for ref in refs:
            pending.append(submit_split(ref))
            if len(pending) >= round_size:
                flush_round()
                pending.clear()
        if pending:
            flush_round()
        return parts

    def _repartition(self, refs: List[Any], k: int) -> Iterator[Any]:
        ray = self._ray()

        def split(block: Block, k: int) -> List[Block]:
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            cuts = [round(i * n / k) for i in range(k + 1)]
            return [acc.slice(cuts[i], cuts[i + 1]) for i in range(k)]

        split_remote = ray.remote(split).options(num_returns=k) if k > 1 else None
        parts = self._exchange_parts(
            refs, lambda ref: split_remote.remote(ref, k), k
        )
        merge = ray.remote(lambda *blocks: BlockAccessor.concat(list(blocks)))
        for i in range(k):
            yield merge.remote(*parts[i]) if parts[i] else ray.put([])

    def _random_shuffle(self, refs, seed, num_blocks) -> Iterator[Any]:
        ray = self._ray()
        k = num_blocks or max(len(refs), 1)
        rng = random.Random(seed)

        def split_shuffled(block: Block, k: int, s: int) -> List[Block]:
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            r = np.random.RandomState(s)
            assign = r.randint(0, k, size=n)
            return [acc.take(np.nonzero(assign == i)[0]) for i in range(k)]

        split_remote = ray.remote(split_shuffled).options(num_returns=k)
        parts = self._exchange_parts(
            refs, lambda ref: split_remote.remote(ref, k, rng.randrange(2**31)), k
        )

        def merge_shuffle(s: int, *blocks: Block) -> Block:
            merged = BlockAccessor.concat(list(blocks))
            acc = BlockAccessor.for_block(merged)
            r = np.random.RandomState(s)
            idx = r.permutation(acc.num_rows())
            return acc.take(idx)

        merge = ray.remote(merge_shuffle)
        for i in range(k):
            s = rng.randrange(2**31)
            yield merge.remote(s, *parts[i]) if parts[i] else ray.put([])

    def _sort(self, refs, key, descending) -> Iterator[Any]:
        """Sample-based range partition + per-partition sort (reference:
        data/_internal/planner/exchange/sort_task_spec.py)."""
        ray = self._ray()
        if not refs:
            return
        k = len(refs)

        def keyvals(block: Block) -> np.ndarray:
            acc = BlockAccessor.for_block(block)
            if callable(key):
                return np.asarray([key(r) for r in acc.iter_rows()])
            if isinstance(block, dict):
                return block[key]
            return np.asarray([r[key] for r in block])

        def sample(block: Block) -> np.ndarray:
            vals = keyvals(block)
            if len(vals) == 0:
                return vals
            idx = np.linspace(0, len(vals) - 1, num=min(20, len(vals))).astype(int)
            return vals[idx]

        samples = ray.get([ray.remote(sample).remote(r) for r in refs])
        allv = np.sort(np.concatenate([s for s in samples if len(s)]))
        if len(allv) == 0:
            yield from refs
            return
        cuts = [allv[round(i * (len(allv) - 1) / k)] for i in range(1, k)]

        def split_range(block: Block, cuts_: List[Any]) -> List[Block]:
            acc = BlockAccessor.for_block(block)
            vals = keyvals(block)
            assign = np.searchsorted(np.asarray(cuts_), vals, side="right")
            return [acc.take(np.nonzero(assign == i)[0]) for i in range(len(cuts_) + 1)]

        split_remote = ray.remote(split_range).options(num_returns=k)
        parts = self._exchange_parts(
            refs, lambda ref: split_remote.remote(ref, cuts), k
        )

        def merge_sorted(*blocks: Block) -> Block:
            merged = BlockAccessor.concat(list(blocks))
            acc = BlockAccessor.for_block(merged)
            if acc.num_rows() == 0:
                return merged
            return acc.take(acc.sort_indices(key, descending))

        merge = ray.remote(merge_sorted)
        order = range(k - 1, -1, -1) if descending else range(k)
        for i in order:
            if parts[i]:
                yield merge.remote(*parts[i])

    def _aggregate(self, refs, key, aggs) -> Iterator[Any]:
        """Hash partition by key + per-partition combine."""
        ray = self._ray()
        k = max(1, min(len(refs), self.ctx.shuffle_partitions))

        def split_hash(block: Block, k: int) -> List[Block]:
            import zlib

            acc = BlockAccessor.for_block(block)
            if key is None:
                return [block] + [acc.slice(0, 0)] * (k - 1)
            if isinstance(block, dict):
                vals = block[key]
            else:
                vals = np.asarray([r[key] for r in block])
            # deterministic cross-process hash: Python's hash() is salted
            # per-process, which would scatter one key over partitions
            hashes = np.asarray(
                [zlib.crc32(repr(v).encode()) % k for v in vals]
            )
            return [acc.take(np.nonzero(hashes == i)[0]) for i in range(k)]

        split_remote = ray.remote(split_hash).options(num_returns=k)
        parts = self._exchange_parts(
            refs, lambda ref: split_remote.remote(ref, k), k
        )

        def combine(key_, aggs_, *blocks: Block) -> Block:
            from ..aggregate import aggregate_block

            merged = BlockAccessor.concat(list(blocks))
            return aggregate_block(merged, key_, aggs_)

        merge = ray.remote(combine)
        for i in range(k):
            if parts[i]:
                yield merge.remote(key, aggs, *parts[i])

    def _zip(self, refs: List[Any], other: L.LogicalOp) -> Iterator[Any]:
        # worker-side merge: the driver only shuffles REFS (r1 Weak
        # finding: both sides used to materialize in the driver)
        ray = self._ray()
        other_refs = list(StreamingExecutor(build_stages(L.LogicalPlan(other))).execute())

        def zip_blocks(n_left: int, *blocks: Block) -> Block:
            left = BlockAccessor.concat(list(blocks[:n_left]))
            right = BlockAccessor.concat(list(blocks[n_left:]))
            la = BlockAccessor.for_block(left)
            ra = BlockAccessor.for_block(right)
            if la.num_rows() != ra.num_rows():
                raise ValueError(
                    f"zip requires equal row counts, got {la.num_rows()} "
                    f"vs {ra.num_rows()}"
                )
            if isinstance(left, dict) and isinstance(right, dict):
                merged = dict(left)
                for c, v in right.items():
                    merged[c if c not in merged else f"{c}_1"] = v
                return merged
            return [
                {**(lr if isinstance(lr, dict) else {"left": lr}),
                 **(rr if isinstance(rr, dict) else {"right": rr})}
                for lr, rr in zip(la.iter_rows(), ra.iter_rows())
            ]

        yield ray.remote(zip_blocks).remote(len(refs), *refs, *other_refs)
