"""Datasources: pluggable readers producing ReadTasks.

Parity: python/ray/data/datasource/ + read_api.py in the reference
(Datasource ABC, ReadTask = zero-arg callable returning blocks +
metadata estimate). Each ReadTask is shipped to a worker by the
streaming executor; IO happens inside tasks, never on the driver.
"""

from __future__ import annotations

import glob as globmod
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from .block import Block, BlockAccessor, BlockMetadata


@dataclass
class ReadTask:
    """A zero-arg callable returning an iterable of Blocks."""

    read_fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata


class Datasource:
    """Parity: data/datasource/datasource.py Datasource ABC."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


class RangeDatasource(Datasource):
    def __init__(self, n: int, block_format: str = "column"):
        self.n = n
        self.block_format = block_format

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n, k = self.n, max(1, min(parallelism, self.n or 1))
        sizes = [n // k + (1 if i < n % k else 0) for i in range(k)]
        tasks, start = [], 0
        for sz in sizes:
            lo, hi = start, start + sz
            start = hi
            if self.block_format == "column":
                fn = lambda lo=lo, hi=hi: [{"id": np.arange(lo, hi)}]
            else:
                fn = lambda lo=lo, hi=hi: [list(range(lo, hi))]
            tasks.append(ReadTask(fn, BlockMetadata(num_rows=sz)))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n, k = len(self.items), max(1, min(parallelism, len(self.items) or 1))
        sizes = [n // k + (1 if i < n % k else 0) for i in range(k)]
        tasks, start = [], 0
        for sz in sizes:
            chunk = self.items[start : start + sz]
            start += sz
            cols = BlockAccessor.batch_to_block(chunk)
            tasks.append(
                ReadTask(
                    lambda c=cols: [c], BlockMetadata(num_rows=sz)
                )
            )
        return tasks


class NumpyDatasource(Datasource):
    def __init__(self, arrays: List[np.ndarray], column: str = "data"):
        self.arrays = arrays
        self.column = column

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        return [
            ReadTask(
                lambda a=a, c=self.column: [{c: a}],
                BlockMetadata(num_rows=len(a), size_bytes=a.nbytes),
            )
            for a in self.arrays
        ]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.path.expanduser(p)
        if os.path.isdir(p):
            # recursive walk, files only (hive-style partition dirs etc.)
            found = []
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if not d.startswith((".", "_"))]
                found.extend(
                    os.path.join(root, f)
                    for f in files
                    if not f.startswith((".", "_"))
                )
            out.extend(sorted(found))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globmod.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files found for {paths}")
    return out


class FileBasedDatasource(Datasource):
    """One ReadTask per file group (parity:
    data/datasource/file_based_datasource.py)."""

    def __init__(self, paths):
        self.paths = _expand_paths(paths)

    def _read_file(self, path: str) -> Block:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        k = max(1, min(parallelism, len(self.paths)))
        groups: List[List[str]] = [[] for _ in range(k)]
        for i, p in enumerate(self.paths):
            groups[i % k].append(p)

        def make(group):
            def read():
                return [self._read_file(p) for p in group]

            return read

        return [
            ReadTask(make(g), BlockMetadata(input_files=g))
            for g in groups
            if g
        ]


class ParquetDatasource(FileBasedDatasource):
    def __init__(self, paths, columns: Optional[List[str]] = None):
        super().__init__(paths)
        self.columns = columns

    def _read_file(self, path: str) -> Block:
        import pyarrow.parquet as pq

        table = pq.read_table(path, columns=self.columns)
        return BlockAccessor.batch_to_block(table)


class CSVDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Block:
        import pyarrow.csv as pacsv

        return BlockAccessor.batch_to_block(pacsv.read_csv(path))


class JSONDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Block:
        import json

        rows = []
        with open(path) as f:
            text = f.read().strip()
        if text.startswith("["):
            rows = json.loads(text)
        else:  # jsonl
            rows = [json.loads(line) for line in text.splitlines() if line.strip()]
        return BlockAccessor.batch_to_block(rows)


class BinaryDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        return [{"path": path, "bytes": data}]


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Block:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        return {"text": np.asarray(lines, dtype=object)}
