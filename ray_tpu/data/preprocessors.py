"""Fit/transform preprocessors over Datasets.

Parity: python/ray/data/preprocessors/ (Preprocessor ABC in
preprocessor.py; scalers.py StandardScaler/MinMaxScaler, encoders.py
OneHotEncoder/LabelEncoder, concatenator.py, chain.py, imputer.py).
Stats are computed with one pass of the Dataset's own aggregation plan
(columnar-numpy blocks), and transforms are plain ``map_batches``
stages — they fuse with neighbouring operators like any other map.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .aggregate import Max, Mean, Min, Std
from .dataset import Dataset


class Preprocessor:
    """fit/transform over Datasets + single-batch transform_batch.

    Subclasses implement ``_fit(ds) -> stats dict`` and
    ``_transform_batch(batch) -> batch``.
    """

    # reference: preprocessor.py Preprocessor.fit_status
    _is_fittable = True

    def __init__(self):
        self.stats_: Optional[Dict[str, Any]] = None

    def fit(self, ds: Dataset) -> "Preprocessor":
        if self._is_fittable:
            self.stats_ = self._fit(ds)
        return self

    def fit_transform(self, ds: Dataset) -> Dataset:
        return self.fit(ds).transform(ds)

    def transform(self, ds: Dataset) -> Dataset:
        self._check_fitted()
        return ds.map_batches(self._transform_batch)

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        self._check_fitted()
        return self._transform_batch(dict(batch))

    def _check_fitted(self) -> None:
        if self._is_fittable and self.stats_ is None:
            raise RuntimeError(
                f"{type(self).__name__} must be fit before transform"
            )

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        raise NotImplementedError

    def _transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(stats={self.stats_})"


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference scalers.py StandardScaler)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        aggs = []
        for c in self.columns:
            aggs += [Mean(c), Std(c)]
        out = ds.aggregate(*aggs)
        return {
            c: (out[f"mean({c})"], out[f"std({c})"] or 1.0) for c in self.columns
        }

    def _transform_batch(self, batch):
        for c in self.columns:
            mean, std = self.stats_[c]
            batch[c] = (np.asarray(batch[c], np.float64) - mean) / (std or 1.0)
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (reference MinMaxScaler)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        aggs = []
        for c in self.columns:
            aggs += [Min(c), Max(c)]
        out = ds.aggregate(*aggs)
        return {c: (out[f"min({c})"], out[f"max({c})"]) for c in self.columns}

    def _transform_batch(self, batch):
        for c in self.columns:
            lo, hi = self.stats_[c]
            span = (hi - lo) or 1.0
            batch[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return batch


def _collect_uniques(ds: Dataset, columns: List[str]) -> Dict[str, np.ndarray]:
    """One pass: per-block uniques, unioned on the driver."""

    def block_uniques(batch):
        uniques = {c: np.unique(batch[c]) for c in columns}
        n = max(len(u) for u in uniques.values())
        out = {}
        for c, u in uniques.items():
            # pad so all columns align into one rectangular block
            pad = np.full(n - len(u), u[-1] if len(u) else 0, dtype=u.dtype)
            out["u_" + c] = np.concatenate([u, pad]) if len(u) else u
        return out

    uniques: Dict[str, List[np.ndarray]] = {c: [] for c in columns}
    for batch in ds.map_batches(block_uniques).iter_batches():
        for c in columns:
            uniques[c].append(np.asarray(batch["u_" + c]))
    return {
        c: np.unique(np.concatenate(v)) if v else np.asarray([])
        for c, v in uniques.items()
    }


class OneHotEncoder(Preprocessor):
    """Expand a categorical column into 0/1 indicator columns
    (reference encoders.py OneHotEncoder: output column ``{col}_{val}``)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        return {c: list(u) for c, u in _collect_uniques(ds, self.columns).items()}

    def _transform_batch(self, batch):
        for c in self.columns:
            vals = np.asarray(batch.pop(c))
            for cat in self.stats_[c]:
                batch[f"{c}_{cat}"] = (vals == cat).astype(np.int8)
        return batch


class LabelEncoder(Preprocessor):
    """Map categorical labels to contiguous ints (reference LabelEncoder)."""

    def __init__(self, label_column: str):
        super().__init__()
        self.label_column = label_column

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        uniques = _collect_uniques(ds, [self.label_column])[self.label_column]
        return {"classes": list(uniques)}

    def _transform_batch(self, batch):
        classes = np.asarray(self.stats_["classes"])
        vals = np.asarray(batch[self.label_column])
        idx = np.searchsorted(classes, vals)
        # validate (searchsorted gives wrong idx silently for unseen)
        bad = (idx >= len(classes)) | (classes[np.clip(idx, 0, len(classes) - 1)] != vals)
        if bad.any():
            raise ValueError(
                f"unseen labels in {self.label_column!r}: "
                f"{np.unique(vals[bad])[:5]}"
            )
        batch[self.label_column] = idx.astype(np.int64)
        return batch


class SimpleImputer(Preprocessor):
    """Fill NaNs with the column mean or a constant (reference imputer.py)."""

    def __init__(self, columns: List[str], strategy: str = "mean", fill_value=None):
        super().__init__()
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unsupported strategy {strategy!r}")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value
        if strategy == "constant":
            self._is_fittable = False

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        # NaN-aware mean: aggregate sum/count over the non-NaN entries
        def nan_stats(batch):
            return {
                f"s_{c}": np.asarray([np.nansum(np.asarray(batch[c], np.float64))])
                for c in self.columns
            } | {
                f"n_{c}": np.asarray(
                    [np.count_nonzero(~np.isnan(np.asarray(batch[c], np.float64)))]
                )
                for c in self.columns
            }

        sums = {c: 0.0 for c in self.columns}
        counts = {c: 0 for c in self.columns}
        for batch in ds.map_batches(nan_stats).iter_batches():
            for c in self.columns:
                sums[c] += float(np.sum(batch[f"s_{c}"]))
                counts[c] += int(np.sum(batch[f"n_{c}"]))
        return {c: (sums[c] / counts[c] if counts[c] else 0.0) for c in self.columns}

    def _transform_batch(self, batch):
        for c in self.columns:
            vals = np.asarray(batch[c], np.float64)
            fill = (
                self.fill_value if self.strategy == "constant" else self.stats_[c]
            )
            batch[c] = np.where(np.isnan(vals), fill, vals)
        return batch


class Concatenator(Preprocessor):
    """Pack feature columns into one 2-D float column (reference
    concatenator.py — the step that makes batches model-ready)."""

    _is_fittable = False

    def __init__(
        self,
        columns: List[str],
        output_column_name: str = "concat_out",
        dtype=np.float32,
    ):
        super().__init__()
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _transform_batch(self, batch):
        parts = []
        for c in self.columns:
            v = np.asarray(batch.pop(c), self.dtype)
            parts.append(v.reshape(len(v), -1))
        batch[self.output_column_name] = np.concatenate(parts, axis=1)
        return batch


class Chain(Preprocessor):
    """Run preprocessors in sequence; fit stages on the progressively
    transformed dataset (reference chain.py semantics)."""

    def __init__(self, *stages: Preprocessor):
        super().__init__()
        self.stages = list(stages)

    def fit(self, ds: Dataset) -> "Chain":
        for stage in self.stages:
            ds = stage.fit_transform(ds)
        self.stats_ = {"fitted": True}
        return self

    def transform(self, ds: Dataset) -> Dataset:
        self._check_fitted()
        for stage in self.stages:
            ds = stage.transform(ds)
        return ds

    def transform_batch(self, batch):
        self._check_fitted()
        for stage in self.stages:
            batch = stage.transform_batch(batch)
        return batch


__all__ = [
    "Preprocessor",
    "StandardScaler",
    "MinMaxScaler",
    "OneHotEncoder",
    "LabelEncoder",
    "SimpleImputer",
    "Concatenator",
    "Chain",
]
