"""ObjectRef: a future for a value in the distributed object store.

Parity: python/ray/includes/object_ref.pxi / ray.ObjectRef in the
reference. Refs are cheap value objects (an id); they re-bind to the
current process's core client when unpickled, so they can flow through
task args, actor calls, and nested data structures.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Optional

from ._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_bin", "_owned", "_shared", "_hold", "__weakref__")

    def __init__(self, object_id: ObjectID, *, _owned: bool = False):
        self._id = object_id
        # raw id bytes, cached at construction: wait() pop-loops rebuild
        # the id list of ~n refs per call (O(n^2) per drain), so the
        # per-ref cost there must be one slot load, not an attr+method
        # chain (single_client_wait_1k_refs)
        self._bin = object_id.binary()
        # strong refs this ref keeps alive: owned twins of args the
        # submitter spilled to the object store — when the caller drops
        # its last return ref, the twins die and ownership GC frees the
        # spilled args (the hub defers while the task is in flight)
        self._hold = None
        # Ownership GC (simplified form of the reference's
        # ReferenceCounter, reference_count.h:43): a ref created by this
        # process's own put()/task submission is "owned"; when the LAST
        # local handle to an owned, never-pickled ref dies, the hub
        # frees the object. Pickling makes borrowers possible, so a
        # shared ref is never auto-freed (it leaks like pre-GC — the
        # conservative direction).
        self._owned = _owned
        self._shared = False

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        self._shared = True  # a copy may now exist anywhere: never auto-free
        return (_rebuild_ref, (self._id.binary(),))

    def __del__(self):
        if not getattr(self, "_owned", False) or getattr(self, "_shared", True):
            return
        try:
            from ._private import worker

            client = worker._client
            if client is not None and not client._closed:
                client.release_owned(self._bin)
        except Exception:
            pass  # interpreter teardown / connection already gone

    # -- convenience -----------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Any:
        from ._private import worker

        return worker.get(self, timeout=timeout)

    def future(self) -> Future:
        """A concurrent.futures.Future resolving to the object's value."""
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self.get())
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    def __await__(self):
        """Support `await ref` inside async actors."""
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _rebuild_ref(id_bytes: bytes) -> ObjectRef:
    return ObjectRef(ObjectID(id_bytes))


class ObjectRefGenerator:
    """Incrementally-resolved refs from a `num_returns="streaming"` task.

    Parity: the reference's ObjectRefGenerator (_raylet.pyx:280) — sync
    and async iteration over ObjectRefs as the remote generator yields;
    a mid-stream exception surfaces as a final ref whose get() raises.
    """

    def __init__(self, task_id: bytes):
        self._task_id = task_id
        self._idx = 0

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        from ._private import protocol as P
        from ._private import worker

        client = worker.get_client()
        reply = client.request(
            P.STREAM_NEXT, {"task_id": self._task_id, "index": self._idx}
        )
        if reply.get("end"):
            raise StopIteration
        self._idx += 1
        return ObjectRef(ObjectID(reply["object_id"]))

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        import asyncio

        def step():
            try:
                return self.__next__()
            except StopIteration:
                return None

        ref = await asyncio.to_thread(step)
        if ref is None:
            raise StopAsyncIteration
        return ref

    def __reduce__(self):
        return (ObjectRefGenerator, (self._task_id,))
