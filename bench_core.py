"""Core-runtime microbenchmarks.

Mirrors the reference's harness (release/microbenchmark/
run_microbenchmark.py -> python/ray/_private/ray_perf.py): same metric
names and shapes as BASELINE.md's table so the ratios are 1:1
comparable. Prints one JSON line per metric:

    {"metric": ..., "value": N, "unit": ..., "platform": ..., "vs_baseline": N}

Every row is stamped with the detected accelerator platform; baselines
are cpu-box numbers, so vs_baseline is refused (null) for rows measured
on any other platform — never compare ratios across hardware.

and a trailing summary line. Baselines were measured on an m4.16xlarge
(64 vCPU); this harness reports whatever hardware it runs on (the CI
box has 1-2 cores), so treat vs_baseline as directional for the
control-plane rows and exact for the in-memory ones.

Run: python bench_core.py [--quick] [--smoke] [--trials N] [--json PATH]

--quick    reduced iteration counts (the mode perf PRs commit
           before/after JSON from; see README "Benchmarking")
--smoke    micro-iterations only: every BASELINES metric still runs and
           is reported, but with counts sized for a CI smoke test
           (tests/test_bench_harness.py); numbers are NOT comparable
--trials N measure every row N times and report the MEDIAN, with the
           per-trial values recorded under "trials" in each JSON row.
           Best-of-1 on a shared box is noise (BENCH_NOTE.md): perf
           evidence should be median-of-3 or better.
--json     also write {"metrics": {...}, "geomean_vs_baseline": N} to
           PATH (the BENCH_pr*_{before,after}.json convention)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINES = {
    "single_client_tasks_sync": 963.0,
    "single_client_tasks_async": 7293.0,
    # net-new rows (no reference analogue), baselines measured on this
    # repo's CI box at their introduction (PR 12):
    # - tasks_bulk: the single_client_tasks_async shape submitted as
    #   ONE SUBMIT_TASKS wire frame via RemoteFunction.map — the
    #   vectorized fan-out path
    # - submit_path_overhead: client-side CPU µs to stage one task
    #   onto the wire (encode + id draw + payload build + frame
    #   pickle), no cluster; LOWER is better (see _LOWER_IS_BETTER)
    "single_client_tasks_bulk": 8315.0,
    "submit_path_overhead": 5.9,
    "multi_client_tasks_async": 22747.0,
    # net-new row (no reference analogue): two client processes
    # submitting concurrently under distinct REGISTERED tenants, so the
    # fairsched ordering/accounting path is on. Baseline measured on
    # this repo's 2-vCPU CI box at the row's introduction (PR 5), not
    # on the m4.16xlarge the reference rows came from.
    "scheduler_contention": 3150.0,
    "1_1_actor_calls_sync": 2043.0,
    "1_1_actor_calls_async": 8120.0,
    "1_1_actor_calls_concurrent": 5396.0,
    "1_n_actor_calls_async": 8164.0,
    "n_n_actor_calls_async": 27273.0,
    "n_n_actor_calls_with_arg_async": 2541.0,
    "1_1_async_actor_calls_sync": 1423.0,
    "1_1_async_actor_calls_async": 4826.0,
    "single_client_get_calls": 10428.0,
    "single_client_put_calls": 4968.0,
    "single_client_put_gigabytes": 19.4,
    "single_client_wait_1k_refs": 4.77,
    # net-new rows (no reference analogue), baselines measured on this
    # repo's 1-core CI box at their introduction (PR 6):
    # - put_gigabytes_direct: a shm-less client streaming large puts
    #   over the out-of-band object plane (object_agent direct put)
    #   instead of the hub-relay PUT_CHUNK path
    # - wait_1k_refs_push: one wait(num_returns=1000) served by the
    #   readiness-push subscription (SUBSCRIBE_READY/READY_PUSH)
    "single_client_put_gigabytes_direct": 1.0,
    "single_client_wait_1k_refs_push": 2.5,
    "placement_group_create_removal": 752.0,
    # net-new row (no reference analogue): throughput RETAINED with
    # runtime tracing head-sampled at 1.0 vs off (single_client_tasks_
    # async shape, each side its own subprocess cluster so init() reads
    # the env). A ratio, so its baseline is 1.0 ("tracing off costs
    # nothing"); reported for evidence, never gated — the gated rows
    # measure the DEFAULT (sampling 0) path, which must stay in the 5%
    # envelope.
    "tracing_overhead": 1.0,
    # net-new row (no reference analogue): throughput RETAINED with the
    # sampling profiler on at its documented default rate (50 Hz in
    # every process) vs off — same subprocess-cluster shape as
    # tracing_overhead. Budget: the ratio must stay above 0.97 (<3%
    # tax); reported for evidence, never gated — the gated rows measure
    # the DEFAULT (RAY_TPU_PROFILE_HZ=0) path, where the profiler is
    # asserted zero-cost by the tier-1 guard (test_profiling.py).
    "profiler_overhead": 1.0,
}

# rows where a SMALLER value is the improvement (latency/overhead
# rows); report() inverts their vs_baseline so >1.0 always means
# "better than baseline" across the table and the geomean
_LOWER_IS_BETTER = {"submit_path_overhead"}

# every BASELINES number was measured on a CPU-backend box; a row
# measured on a different accelerator platform is not comparable, so
# report() stamps the detected platform into each row and refuses the
# ratio (vs_baseline = None) on a mismatch rather than emitting a
# cross-platform geomean that looks like a regression/speedup
BASELINE_PLATFORM = "cpu"


def _detect_platform() -> str:
    """Backend the bench is running against. Only consults jax if the
    run already imported it (importing jax here would skew rows);
    otherwise trusts JAX_PLATFORMS, defaulting to cpu."""
    if "jax" in sys.modules:
        try:
            return sys.modules["jax"].default_backend()
        except Exception:  # noqa: BLE001 — detection must never fail a run
            pass
    env = os.environ.get("JAX_PLATFORMS", "").strip()
    if env:
        return env.split(",")[0].strip() or "cpu"
    return "cpu"

SMOKE = False
QUICK = False
TRIALS = None  # --trials N: median-of-N, per-trial values in the JSON
JSON_PATH = None
RESULTS = []


def _parse_argv(argv) -> None:
    """Flag parsing stays out of import time: tests import this module
    for BASELINES, and pytest's argv must neither configure a bench
    mode nor trip the --json validation sys.exit at collection."""
    global SMOKE, QUICK, TRIALS, JSON_PATH
    SMOKE = "--smoke" in argv
    QUICK = "--quick" in argv or SMOKE
    if "--trials" in argv:
        try:
            TRIALS = int(argv[argv.index("--trials") + 1])
        except (IndexError, ValueError):
            sys.exit("--trials requires an integer argument")
        if TRIALS < 1:
            sys.exit("--trials must be >= 1")
    if "--json" in argv:
        try:
            JSON_PATH = argv[argv.index("--json") + 1]
        except IndexError:
            sys.exit("--json requires a path argument")
        if JSON_PATH.startswith("-"):
            sys.exit(
                f"--json requires a path argument, got flag {JSON_PATH!r}"
            )


def report(metric: str, value, unit: str) -> None:
    trials_list = None
    if isinstance(value, list):  # --trials mode: timeit returned samples
        trials_list = [round(v, 3) for v in value]
        value = float(np.median(value))
    platform = _detect_platform()
    base = BASELINES.get(metric)
    if platform != BASELINE_PLATFORM:
        # baselines are cpu-box numbers: a tpu/gpu row may not be
        # ratioed against them (the geomean would mix hardware)
        ratio = None
    elif base and metric in _LOWER_IS_BETTER:
        ratio = base / value
    elif base:
        ratio = value / base
    else:
        ratio = None
    rec = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "platform": platform,
        "vs_baseline": round(ratio, 3) if ratio else None,
    }
    if trials_list is not None:
        rec["trials"] = trials_list
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def timeit(fn, warmup: int = 1, trials: int = 3):
    """ops/s from fn() -> ops count. Default: best-of-trials (one trial
    in --quick mode). With --trials N: the N per-trial values are
    returned as a list and report() records median + all samples —
    best-of-1 noise on a loaded box is exactly what multi-trial
    medians exist to kill (BENCH_NOTE.md)."""
    for _ in range(warmup):
        fn()
    if TRIALS:
        samples = []
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            n = fn()
            dt = time.perf_counter() - t0
            samples.append(n / dt)
        return samples
    best = 0.0
    for _ in range(1 if QUICK else trials):
        t0 = time.perf_counter()
        n = fn()
        dt = time.perf_counter() - t0
        best = max(best, n / dt)
    return best


def main() -> None:
    import ray_tpu

    ray_tpu.init(num_cpus=8, max_workers=4 if SMOKE else 8)

    @ray_tpu.remote
    def nullary():
        return b"ok"

    @ray_tpu.remote
    class Sink:
        def ping(self):
            return b"ok"

        def sink(self, *args):
            return b"ok"

    @ray_tpu.remote
    class AsyncSink:
        async def ping(self):
            return b"ok"

    # A submitting client that lives in its own worker process — the
    # reference's multi-client rows measure multi-PROCESS submission
    # (ray_perf.py Client actor / work() tasks), not driver threads.
    @ray_tpu.remote
    class Client:
        def __init__(self, targets=None, tenant=None):
            self.targets = targets or []
            # scheduler_contention row: each submitting client stamps
            # its own tenant so the hub's fairsched path does real work
            self.fn = nullary.options(tenant=tenant) if tenant else nullary

        def task_batch(self, n):
            ray_tpu.get([self.fn.remote() for _ in range(n)])
            return n

        def call_batch(self, n):
            refs = []
            for i in range(n):
                refs.append(self.targets[i % len(self.targets)].ping.remote())
            ray_tpu.get(refs)
            return n

        def arg_batch(self, n):
            # reference shape (ray_perf.py:51 small_value_batch_arg):
            # put a SMALL value once per batch, pass the REF to every
            # call on every server
            x = ray_tpu.put(0)
            ray_tpu.get(
                [t.sink.remote(x) for t in self.targets for _ in range(n)]
            )
            return n * len(self.targets)

    # warm the worker pool so spawn latency isn't measured
    ray_tpu.get([nullary.remote() for _ in range(4 if SMOKE else 16)])

    N_SYNC = 10 if SMOKE else (200 if QUICK else 1000)
    N_ASYNC = 40 if SMOKE else (2000 if QUICK else 10000)
    N_CLIENTS = 2 if SMOKE else 4

    def tasks_sync():
        for _ in range(N_SYNC):
            ray_tpu.get(nullary.remote())
        return N_SYNC

    report("single_client_tasks_sync", timeit(tasks_sync), "tasks/s")

    def tasks_async():
        ray_tpu.get([nullary.remote() for _ in range(N_ASYNC)])
        return N_ASYNC

    report("single_client_tasks_async", timeit(tasks_async), "tasks/s")

    def tasks_bulk():
        # same shape as tasks_async but all N tasks ride ONE
        # SUBMIT_TASKS frame (RemoteFunction.map): one encode of the
        # shared fields, one id slab, one hub admission pass
        ray_tpu.get(nullary.map([()] * N_ASYNC))
        return N_ASYNC

    report("single_client_tasks_bulk", timeit(tasks_bulk), "tasks/s")

    def submit_path():
        # client-side CPU to stage tasks onto the wire, measured as the
        # PR 18 template-spliced path actually pays it: the frame
        # PREFIX (fn_id/resources/options) is built once per template —
        # cached, amortized to ~zero — so each call costs encode_args +
        # an id draw + one hand-emitted pickle fragment, and each
        # drained batch one opcode splice. No sockets, so this isolates
        # per-call submit overhead from scheduler + worker time.
        from ray_tpu._private import protocol as _P
        from ray_tpu._private.ids import id_pair
        from ray_tpu._private.serialization import (
            close_submit_frame,
            submit_frame_prefix,
            task_entry_fragment,
        )
        from ray_tpu.remote_function import encode_args

        n = 64 if SMOKE else 4096
        prefix = submit_frame_prefix(_P.SUBMIT_TASKS, {
            "fn_id": "bench_fn",
            "resources": {"CPU": 1.0},
            "options": {"max_retries": 3},
            "pipeline": False,
        })
        assert prefix is not None
        frags = []
        append = frags.append
        for i in range(n):
            kind, payload, deps, _holds = encode_args(None, (i,), {})
            tid, rid = id_pair()
            append(task_entry_fragment(tid, kind, payload, deps, (rid,)))
        close_submit_frame(prefix, frags, req_id=1)
        return n

    rate = timeit(submit_path)
    report(
        "submit_path_overhead",
        [1e6 / r for r in rate] if isinstance(rate, list) else 1e6 / rate,
        "us/task",
    )

    # 4 client processes each submitting a quarter of the tasks
    # (reference shape: ray_perf.py "multi client tasks async")
    task_clients = [Client.remote() for _ in range(N_CLIENTS)]
    ray_tpu.get([c.task_batch.remote(4) for c in task_clients])

    def tasks_multi():
        ray_tpu.get(
            [c.task_batch.remote(N_ASYNC // N_CLIENTS) for c in task_clients]
        )
        return N_ASYNC

    report("multi_client_tasks_async", timeit(tasks_multi), "tasks/s")

    # ---- actors
    a = Sink.remote()
    ray_tpu.get(a.ping.remote())

    def actor_sync():
        for _ in range(N_SYNC):
            ray_tpu.get(a.ping.remote())
        return N_SYNC

    report("1_1_actor_calls_sync", timeit(actor_sync), "calls/s")

    def actor_async():
        ray_tpu.get([a.ping.remote() for _ in range(N_ASYNC)])
        return N_ASYNC

    report("1_1_actor_calls_async", timeit(actor_async), "calls/s")

    conc = Sink.options(max_concurrency=4).remote()
    ray_tpu.get(conc.ping.remote())

    def actor_concurrent():
        ray_tpu.get([conc.ping.remote() for _ in range(N_ASYNC)])
        return N_ASYNC

    report("1_1_actor_calls_concurrent", timeit(actor_concurrent), "calls/s")

    n_actors = N_CLIENTS
    actors = [Sink.remote() for _ in range(n_actors)]
    ray_tpu.get([x.ping.remote() for x in actors])

    # one client process driving all n actors (reference shape:
    # "1:n actor calls async" — Client.small_value_batch)
    one_n_client = Client.remote(actors)
    ray_tpu.get(one_n_client.call_batch.remote(n_actors))

    def one_n_async():
        ray_tpu.get(one_n_client.call_batch.remote(N_ASYNC))
        return N_ASYNC

    report("1_n_actor_calls_async", timeit(one_n_async), "calls/s")

    # m client processes each driving all n actors (reference shape:
    # "n:n actor calls async" — m work() tasks over n_cpu actors)
    nn_clients = [Client.remote(actors) for _ in range(n_actors)]
    ray_tpu.get([c.call_batch.remote(n_actors) for c in nn_clients])

    def n_n_async():
        ray_tpu.get(
            [c.call_batch.remote(N_ASYNC // n_actors) for c in nn_clients]
        )
        return N_ASYNC

    report("n_n_actor_calls_async", timeit(n_n_async), "calls/s")

    N_ARG = N_ASYNC // 10

    # client processes each putting a small object and fanning the ref
    # out to every server actor (reference shape: "n:n actor calls with
    # arg async" — Client.small_value_batch_arg over all servers,
    # ray_perf.py:51,238)
    arg_clients = [Client.remote(actors) for _ in range(n_actors)]
    ray_tpu.get([c.arg_batch.remote(1) for c in arg_clients])
    per_client = max(1, N_ARG // (n_actors * n_actors))

    def n_n_with_arg():
        ray_tpu.get(
            [c.arg_batch.remote(per_client) for c in arg_clients]
        )
        return per_client * n_actors * n_actors

    report("n_n_actor_calls_with_arg_async", timeit(n_n_with_arg), "calls/s")

    aa = AsyncSink.remote()
    ray_tpu.get(aa.ping.remote())

    def async_actor_sync():
        for _ in range(N_SYNC):
            ray_tpu.get(aa.ping.remote())
        return N_SYNC

    report("1_1_async_actor_calls_sync", timeit(async_actor_sync), "calls/s")

    def async_actor_async():
        ray_tpu.get([aa.ping.remote() for _ in range(N_ASYNC)])
        return N_ASYNC

    report("1_1_async_actor_calls_async", timeit(async_actor_async), "calls/s")

    # ---- object store
    small = b"x" * 1024
    small_ref = ray_tpu.put(small)

    def get_calls():
        for _ in range(N_SYNC):
            ray_tpu.get(small_ref)
        return N_SYNC

    # note: reference's get benchmark re-gets the same object too
    report("single_client_get_calls", timeit(get_calls), "ops/s")

    def put_calls():
        for _ in range(N_SYNC):
            ray_tpu.put(small)
        return N_SYNC

    report("single_client_put_calls", timeit(put_calls), "ops/s")

    big = np.random.randint(
        0, 256, (4 * 1024 * 1024 if SMOKE else 256 * 1024 * 1024,),
        dtype=np.uint8,
    )

    def put_gb():
        # free between puts: sustained throughput with the object
        # lifecycle, not unbounded tmpfs accumulation (this sandbox
        # throttles fresh-page allocation past ~1.2 GB)
        n = 2 if QUICK else 4
        for _ in range(n):
            ray_tpu.free([ray_tpu.put(big)])
        return n * big.nbytes / (1024**3)

    report("single_client_put_gigabytes", timeit(put_gb, warmup=0), "GiB/s")

    def wait_1k():
        # reference shape (ray_perf.py wait_multiple_refs): pop one
        # ready ref per wait() call until all 1000 are drained
        n = 1 if QUICK else 3
        for _ in range(n):
            not_ready = [nullary.remote() for _ in range(100 if SMOKE else 1000)]
            while not_ready:
                _ready, not_ready = ray_tpu.wait(not_ready, timeout=60)
        return n

    report("single_client_wait_1k_refs", timeit(wait_1k, warmup=0), "ops/s")

    def wait_1k_push():
        # readiness-push-native shape: ONE wait for the full set — a
        # single SUBSCRIBE_READY round trip plus hub pushes, no
        # pop-loop re-asks (PR 6 out-of-band object plane)
        n = 1 if QUICK else 3
        for _ in range(n):
            count = 100 if SMOKE else 1000
            refs = [nullary.remote() for _ in range(count)]
            ready, _ = ray_tpu.wait(refs, num_returns=count, timeout=60)
            assert len(ready) == count
        return n

    report(
        "single_client_wait_1k_refs_push", timeit(wait_1k_push, warmup=0),
        "ops/s",
    )

    # ---- placement groups
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    def pg_churn():
        n = 5 if SMOKE else (50 if QUICK else 200)
        for _ in range(n):
            pg = placement_group([{"CPU": 0.01}])
            pg.wait(10)
            remove_placement_group(pg)
        return n

    report("placement_group_create_removal", timeit(pg_churn, warmup=0), "pg/s")

    # ---- multi-tenant scheduler contention (LAST: registering tenants
    # turns the fairsched accounting path on for the rest of the
    # session, and the single-tenant rows above must stay inert-path)
    # Two client processes submit concurrently under distinct
    # registered tenants, so quota admission + fair-share class
    # ordering + usage accounting all run on the dispatch hot path.
    from ray_tpu._private import worker as _worker

    _bench_client = _worker.get_client()
    _bench_client.register_job("bench-job-a", tenant="bench-a")
    _bench_client.register_job("bench-job-b", tenant="bench-b")
    contention = [Client.remote(tenant=f"bench-{t}") for t in ("a", "b")]
    ray_tpu.get([c.task_batch.remote(4) for c in contention])

    def sched_contention():
        ray_tpu.get(
            [c.task_batch.remote(N_ASYNC // 2) for c in contention]
        )
        return N_ASYNC

    report("scheduler_contention", timeit(sched_contention), "tasks/s")

    if SMOKE:
        # smoke must still report every BASELINES row: exercise the
        # direct-put plane in-process against this session's head agent
        # (numbers NOT comparable to quick/full subprocess runs)
        _smoke_direct_put_row()

    ray_tpu.shutdown()

    # tracing overhead: both sides need a FRESH cluster (sampling is
    # read at init), so this runs after the main session is down
    _bench_tracing_overhead()
    # likewise the profiler: the sample rate is read at process start
    _bench_profiler_overhead()

    if not SMOKE:
        _bench_client_mode()

    # geomean only over baseline-platform rows (off-platform rows carry
    # vs_baseline=None by construction, so the filter is the same — but
    # say so rather than rely on it silently)
    ratios = [r["vs_baseline"] for r in RESULTS
              if r["vs_baseline"] and r.get("platform") == BASELINE_PLATFORM]
    geomean = float(np.exp(np.mean(np.log(ratios)))) if ratios else 0.0
    summary = {
        "metric": "core_microbench_geomean_vs_baseline",
        "value": round(geomean, 3),
        "unit": "ratio",
        "platform": _detect_platform(),
        "vs_baseline": round(geomean, 3),
        "detail": {r["metric"]: r["value"] for r in RESULTS},
    }
    print(json.dumps(summary))
    if JSON_PATH:
        with open(JSON_PATH, "w") as f:
            json.dump(
                {
                    "mode": "smoke" if SMOKE else ("quick" if QUICK else "full"),
                    "trials": TRIALS or 1,
                    "platform": _detect_platform(),
                    "metrics": {r["metric"]: r for r in RESULTS},
                    "geomean_vs_baseline": round(geomean, 3),
                },
                f, indent=2,
            )
            f.write("\n")


def _smoke_direct_put_row() -> None:
    """Tiny in-process direct put for the --smoke BASELINES contract
    (a scratch shm-less client streaming to this session's object
    agent — same code path as the quick/full subprocess row)."""
    import tempfile
    import time as _time
    import uuid

    import numpy as np

    from ray_tpu._private import worker as w
    from ray_tpu._private.client import CoreClient

    hub = w._hub
    scratch = os.path.join(
        tempfile.gettempdir(), f"rt_bench_{uuid.uuid4().hex[:8]}"
    )
    os.makedirs(scratch, exist_ok=True)
    cl = CoreClient(hub.addr, scratch, role="client",
                    worker_id="bench_smoke_client")
    cl.inline_only = True
    cl.hostname = "bench-smoke-remote"  # force the socket path
    try:
        big = np.random.randint(0, 256, (4 * 1024 * 1024,), dtype=np.uint8)
        cl.free([cl.put_value(big)])  # warm the path

        def one_trial():
            t0 = _time.perf_counter()
            n = 4
            for _ in range(n):
                cl.free([cl.put_value(big)])
            return n * big.nbytes / (1024 ** 3) / (
                _time.perf_counter() - t0
            )

        samples = [one_trial() for _ in range(TRIALS or 1)]
        report(
            "single_client_put_gigabytes_direct",
            samples if TRIALS else samples[0], "GiB/s",
        )
    finally:
        cl.close()


def _tasks_async_rate(env_extra: dict, n: int) -> float:
    """One self-contained subprocess cluster running the
    single_client_tasks_async shape; returns tasks/s. Used by the
    tracing_overhead row: sampling is read at init, so on/off must be
    separate processes (serial, same box — BENCH_NOTE.md)."""
    import subprocess

    script = f"""
import sys; sys.path.insert(0, {json.dumps(os.path.dirname(os.path.abspath(__file__)))})
import time
import ray_tpu
ray_tpu.init(num_cpus=4, max_workers=2)

@ray_tpu.remote
def nullary():
    return b"ok"

ray_tpu.get([nullary.remote() for _ in range(8)])  # warm the pool
n = {n}
t0 = time.perf_counter()
ray_tpu.get([nullary.remote() for _ in range(n)])
print("RATE", n / (time.perf_counter() - t0))
ray_tpu.shutdown()
"""
    env = {**os.environ, **env_extra}
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True,
        text=True, timeout=300, env=env,
    )
    rate = next(
        (float(line.split()[1]) for line in out.stdout.splitlines()
         if line.startswith("RATE")),
        None,
    )
    if rate is None:
        # surface the child's actual failure, not a bare StopIteration
        raise RuntimeError(
            f"bench subprocess rc={out.returncode}: "
            f"{(out.stderr or out.stdout)[-400:]}"
        )
    return rate


def _bench_tracing_overhead() -> None:
    """tracing_overhead row: single_client_tasks_async with runtime
    head-sampling at 1.0 vs off, reported as the on/off throughput
    RATIO (1.0 = free; documented, not gated). Off runs first, with
    --trials both sides run TRIALS times (off reduced to its median so
    per-trial samples express the SAMPLED side's spread)."""
    n = 40 if SMOKE else (1000 if QUICK else 5000)
    off_env = {"RAY_TPU_TRACE_SAMPLE": "0", "RAY_TPU_TRACING": "0"}
    on_env = {"RAY_TPU_TRACE_SAMPLE": "1.0"}
    try:
        off = [_tasks_async_rate(off_env, n) for _ in range(TRIALS or 1)]
        off_med = float(np.median(off))
        on = [_tasks_async_rate(on_env, n) for _ in range(TRIALS or 1)]
    except Exception as e:  # noqa: BLE001
        print(f"tracing_overhead failed: {e}", file=sys.stderr)
        return
    samples = [r / off_med for r in on]
    report(
        "tracing_overhead",
        samples if TRIALS else samples[0], "ratio",
    )


def _bench_profiler_overhead() -> None:
    """profiler_overhead row: single_client_tasks_async with the
    sampling profiler at 50 Hz vs off, reported as the on/off
    throughput RATIO (1.0 = free; <3% tax budgeted). Same serial
    subprocess-cluster protocol as tracing_overhead: RAY_TPU_PROFILE_HZ
    is read at process start, so each side is its own cluster."""
    n = 40 if SMOKE else (1000 if QUICK else 5000)
    off_env = {"RAY_TPU_PROFILE_HZ": "0"}
    on_env = {"RAY_TPU_PROFILE_HZ": "50"}
    try:
        off = [_tasks_async_rate(off_env, n) for _ in range(TRIALS or 1)]
        off_med = float(np.median(off))
        on = [_tasks_async_rate(on_env, n) for _ in range(TRIALS or 1)]
    except Exception as e:  # noqa: BLE001
        print(f"profiler_overhead failed: {e}", file=sys.stderr)
        return
    samples = [r / off_med for r in on]
    report(
        "profiler_overhead",
        samples if TRIALS else samples[0], "ratio",
    )


def _client_put_rate(address: str, env_extra: dict) -> float:
    """One shm-less client subprocess streaming large puts; returns
    GiB/s (the direct plane or the hub relay, per env_extra)."""
    import subprocess

    script = f"""
import sys; sys.path.insert(0, {json.dumps(os.path.dirname(os.path.abspath(__file__)))})
import time
import numpy as np
import ray_tpu
ray_tpu.init(address={json.dumps(address)})
big = np.random.randint(0, 256, (64 * 1024 * 1024,), dtype=np.uint8)
ray_tpu.free([ray_tpu.put(big)])  # warm the path
n = {2 if QUICK else 8}
t0 = time.perf_counter()
for _ in range(n):
    ray_tpu.free([ray_tpu.put(big)])
dt = time.perf_counter() - t0
print("RATE", n * big.nbytes / (1024 ** 3) / dt)
ray_tpu.shutdown()
"""
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True,
        text=True, timeout=300, env={**os.environ, **env_extra},
    )
    return next(
        float(line.split()[1]) for line in out.stdout.splitlines()
        if line.startswith("RATE")
    )


def _bench_client_mode() -> None:
    # ---- client-mode object plane (the direct row has a this-box
    # baseline; the relay row keeps its original no-baseline provenance
    # — it documents the PUT_CHUNK hub-relay path the direct plane
    # falls back to)
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=2, max_workers=2, _tcp_hub=True)
    addr = ctx.address_info["address"]
    try:
        # --trials applies here too: each trial is one client
        # subprocess run, so these rows carry the same median +
        # per-trial evidence as the in-process ones
        for metric, env_extra in (
            ("single_client_put_gigabytes_direct",
             {"RAY_TPU_OBJECT_DIRECT": "1"}),
            ("client_put_gigabytes", {"RAY_TPU_OBJECT_DIRECT": "0"}),
        ):
            try:
                samples = [
                    _client_put_rate(addr, env_extra)
                    for _ in range(TRIALS or 1)
                ]
                report(metric, samples if TRIALS else samples[0], "GiB/s")
            except Exception as e:  # noqa: BLE001
                print(f"{metric} failed: {e}", file=sys.stderr)
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    _parse_argv(sys.argv[1:])
    main()
