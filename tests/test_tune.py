"""Tune tests (pattern: python/ray/tune/tests/ — tiny function
trainables on a real runtime; scheduler/searcher behavioral asserts)."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, RunConfig
from ray_tpu.tune import (
    ASHAScheduler,
    PopulationBasedTraining,
    TuneConfig,
    Tuner,
)


@pytest.fixture
def storage(tmp_path):
    return str(tmp_path / "tune")


def test_grid_search_runs_all(ray_start_4_cpus, storage):
    def trainable(config):
        tune.report({"score": config["a"] * 10 + config["b"]})

    tuner = Tuner(
        trainable,
        param_space={"a": tune.grid_search([1, 2, 3]), "b": tune.grid_search([0, 1])},
        tune_config=TuneConfig(metric="score", mode="max", max_concurrent_trials=2),
        run_config=RunConfig(name="grid", storage_path=storage),
    )
    results = tuner.fit()
    assert len(results) == 6
    best = results.get_best_result()
    assert best.metrics["score"] == 31
    assert best.metrics["config"] == {"a": 3, "b": 1}


def test_random_sampling(ray_start_4_cpus, storage):
    def trainable(config):
        tune.report({"v": config["lr"]})

    tuner = Tuner(
        trainable,
        param_space={"lr": tune.loguniform(1e-5, 1e-1)},
        tune_config=TuneConfig(metric="v", mode="min", num_samples=4, seed=7),
        run_config=RunConfig(name="rand", storage_path=storage),
    )
    results = tuner.fit()
    assert len(results) == 4
    vals = [r.metrics["v"] for r in results]
    assert all(1e-5 <= v <= 1e-1 for v in vals)
    assert len(set(vals)) > 1  # actually sampled


def test_trial_error_isolated(ray_start_4_cpus, storage):
    def trainable(config):
        if config["x"] == 1:
            raise ValueError("trial poisoned")
        tune.report({"ok": config["x"]})

    results = Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=TuneConfig(metric="ok", mode="max"),
        run_config=RunConfig(name="err", storage_path=storage),
    ).fit()
    assert len(results) == 3
    assert len(results.errors) == 1
    assert results.get_best_result().metrics["ok"] == 2


def test_asha_stops_bad_trials(ray_start_4_cpus, storage):
    def trainable(config):
        for i in range(20):
            # bad trials plateau high; good trials descend
            loss = config["base"] - (i * 0.1 if config["base"] < 5 else 0.0)
            tune.report({"loss": loss})

    sched = ASHAScheduler(metric="loss", mode="min", max_t=20, grace_period=2, reduction_factor=2)
    results = Tuner(
        trainable,
        param_space={"base": tune.grid_search([1.0, 2.0, 8.0, 9.0])},
        tune_config=TuneConfig(metric="loss", mode="min", scheduler=sched,
                               max_concurrent_trials=4),
        run_config=RunConfig(name="asha", storage_path=storage),
    ).fit()
    assert len(results) == 4
    # the bad trials must have been stopped before finishing 20 iters
    iters = {r.metrics["config"]["base"]: r.metrics["training_iteration"] for r in results}
    assert iters[8.0] < 20 or iters[9.0] < 20
    assert results.get_best_result().metrics["config"]["base"] == 1.0


def test_checkpointed_trials(ray_start_4_cpus, storage):
    def trainable(config):
        ckpt = tune.get_checkpoint()
        start = ckpt.to_state()["i"] + 1 if ckpt else 0
        for i in range(start, 3):
            tune.report({"i": i}, checkpoint=Checkpoint.from_state({"i": i}))

    results = Tuner(
        trainable,
        param_space={"x": tune.grid_search([0])},
        tune_config=TuneConfig(metric="i", mode="max"),
        run_config=RunConfig(name="ckpt", storage_path=storage),
    ).fit()
    r = results[0]
    assert r.checkpoint is not None
    assert r.checkpoint.to_state()["i"] == 2


def test_pbt_exploits(ray_start_4_cpus, storage):
    """Bottom trial adopts top trial's checkpoint + mutated config."""

    def trainable(config):
        ckpt = tune.get_checkpoint()
        level = ckpt.to_state()["level"] if ckpt else 0.0
        for i in range(12):
            level += config["rate"]
            tune.report(
                {"score": level},
                checkpoint=Checkpoint.from_state({"level": level}),
            )

    sched = PopulationBasedTraining(
        metric="score",
        mode="max",
        perturbation_interval=3,
        hyperparam_mutations={"rate": tune.uniform(0.5, 2.0)},
        quantile_fraction=0.5,
        seed=3,
    )
    results = Tuner(
        trainable,
        param_space={"rate": tune.grid_search([0.01, 2.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=sched,
                               max_concurrent_trials=2),
        run_config=RunConfig(name="pbt", storage_path=storage),
    ).fit()
    best = results.get_best_result()
    # the slow trial exploited the fast one, so both finish far above
    # what rate=0.01 alone could reach (12 * 0.01 = 0.12)
    scores = sorted(r.metrics["score"] for r in results)
    assert scores[0] > 1.0


def test_tuner_wraps_trainer(ray_start_4_cpus, storage):
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def loop(config):
        from ray_tpu import train

        train.report({"out": config["m"] * 2})

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="inner", storage_path=storage),
    )
    results = Tuner(
        trainer,
        param_space={"m": tune.grid_search([3, 5])},
        tune_config=TuneConfig(metric="out", mode="max", max_concurrent_trials=1),
        run_config=RunConfig(name="wrap", storage_path=storage),
    ).fit()
    assert results.get_best_result().metrics["out"] == 10


def test_tuner_restore_resumes_from_checkpoints(ray_start_4_cpus, storage, tmp_path):
    """Kill-and-resume (reference: Tuner.restore over
    experiment_state.py): trials crash mid-run; Tuner.restore rehydrates
    searcher/scheduler/trial state and continues each trial from its
    last checkpoint instead of from scratch."""
    crash_dir = str(tmp_path / "markers")
    os.makedirs(crash_dir, exist_ok=True)

    def trainable(config):
        ckpt = tune.get_checkpoint()
        start = ckpt.to_state()["i"] + 1 if ckpt else 0
        marker = os.path.join(crash_dir, f"trial_{config['x']}")
        for i in range(start, 6):
            # record every executed step for the no-redo assertion
            with open(marker, "a") as f:
                f.write(f"{i},")
            tune.report({"i": i}, checkpoint=Checkpoint.from_state({"i": i}))
            if i == 2 and not os.path.exists(marker + ".crashed"):
                open(marker + ".crashed", "w").close()
                os._exit(1)  # hard crash mid-experiment

    tuner = Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1])},
        tune_config=TuneConfig(metric="i", mode="max"),
        run_config=RunConfig(name="resume_exp", storage_path=storage),
    )
    results = tuner.fit()
    assert len(results.errors) == 2  # both trials crashed

    exp_dir = os.path.join(storage, "resume_exp")
    assert Tuner.can_restore(exp_dir)
    restored = Tuner.restore(exp_dir, trainable, restart_errored=True)
    results2 = restored.fit()
    assert not results2.errors
    assert len(results2) == 2  # no extra trials suggested after restore
    for r in results2:
        assert r.metrics["i"] == 5
        assert r.checkpoint.to_state()["i"] == 5
    # resumed from a checkpoint, not from scratch: early steps ran
    # exactly once (only the step(s) after the last durable checkpoint
    # may replay — that's the recovery contract)
    for x in (0, 1):
        steps = open(os.path.join(crash_dir, f"trial_{x}")).read()
        executed = [int(s) for s in steps.strip(",").split(",")]
        assert executed[-1] == 5
        assert executed.count(0) == 1 and executed.count(1) == 1, executed
        assert len(executed) <= 8, executed  # 6 steps + <=2 replays


def test_tuner_restore_keeps_finished_results(ray_start_4_cpus, storage):
    def trainable(config):
        tune.report({"score": config["x"]})

    Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="done_exp", storage_path=storage),
    ).fit()
    exp_dir = os.path.join(storage, "done_exp")
    restored = Tuner.restore(exp_dir, trainable)
    results = restored.fit()  # nothing to do: results come from state
    assert len(results) == 2
    assert results.get_best_result().metrics["score"] == 2


def test_pb2_gp_proposals_track_good_region():
    """PB2 unit behavior: with history showing reward improvement
    peaking at lr~0.5, the GP-UCB proposal lands near it and always
    inside the bounds (reference: tune/schedulers/pb2.py)."""
    import numpy as np

    from ray_tpu.tune import PB2

    sched = PB2(metric="score", mode="max",
                hyperparam_bounds={"lr": (0.0, 1.0)},
                perturbation_interval=1, seed=0)
    # simulate a population whose per-step improvement = -(lr-0.5)^2
    score = {f"t{i}": 0.0 for i in range(4)}
    lrs = {"t0": 0.05, "t1": 0.35, "t2": 0.55, "t3": 0.95}
    for tid, lr in lrs.items():
        sched.register_config(tid, {"lr": lr})
    for step in range(1, 6):
        for tid, lr in lrs.items():
            score[tid] += 1.0 - (lr - 0.5) ** 2
            sched.on_result(tid, {"score": score[tid],
                                  "training_iteration": step})
    props = [sched._mutate({"lr": 0.1})["lr"] for _ in range(8)]
    assert all(0.0 <= p <= 1.0 for p in props)
    # GP mean peaks near 0.5; with modest UCB exploration most
    # proposals concentrate around it
    assert abs(float(np.median(props)) - 0.5) < 0.25, props


def test_pb2_end_to_end_tuner(ray_start_4_cpus):
    """PB2 drives a real Tuner run (exploit/explore through checkpoint
    cloning, like the PBT integration path)."""
    from ray_tpu import train, tune
    from ray_tpu.tune import PB2

    def trainable(config):
        value = 0.0
        for it in range(6):
            value += 1.0 - (config["lr"] - 0.5) ** 2
            train.report({"score": value})

    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=4,
            scheduler=PB2(hyperparam_bounds={"lr": (0.0, 1.0)},
                          perturbation_interval=2, seed=1),
        ),
    )
    results = tuner.fit()
    assert len(results) == 4
    assert results.get_best_result().metrics["score"] > 0


def test_concurrency_limiter_caps_inflight(ray_start_4_cpus, tmp_path):
    """ConcurrencyLimiter (reference: tune/search/concurrency_limiter.py):
    never more than max_concurrent trials hold a live suggestion, and
    completions release slots so every sample still runs."""
    import json
    import os

    from ray_tpu import tune
    from ray_tpu.tune import ConcurrencyLimiter
    from ray_tpu.tune.search import BasicVariantGenerator

    peak_file = tmp_path / "peak.json"
    peak_file.write_text("0")
    live_file = tmp_path / "live.json"
    live_file.write_text("0")

    def trainable(config):
        import fcntl
        import time

        # track max concurrently-RUNNING trials via a lock-guarded file
        def bump(delta):
            with open(live_file, "r+") as f:
                fcntl.flock(f, fcntl.LOCK_EX)
                live = int(f.read() or 0) + delta
                f.seek(0); f.truncate(); f.write(str(live))
                peak = int(peak_file.read_text() or 0)
                if live > peak:
                    peak_file.write_text(str(live))
            return live

        bump(+1)
        time.sleep(0.3)
        bump(-1)
        tune.report({"loss": config["x"]})

    from ray_tpu.train import RunConfig
    from ray_tpu.tune import TuneConfig, Tuner

    base = BasicVariantGenerator({"x": tune.grid_search([1, 2, 3, 4, 5])})
    tuner = Tuner(
        trainable,
        tune_config=TuneConfig(
            search_alg=ConcurrencyLimiter(base, max_concurrent=2),
            metric="loss", mode="min", num_samples=5,
        ),
        run_config=RunConfig(name="climit", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 5              # every sample still ran
    assert results.get_best_result().metrics["loss"] == 1
    assert int(peak_file.read_text()) <= 2, "cap exceeded"
