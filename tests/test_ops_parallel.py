"""Tests for parallelism ops: flash/ring/Ulysses attention, MoE,
pipeline. All run on the virtual 8-device CPU mesh (conftest), the
pattern SURVEY.md §4.5 calls out for testing collectives without
accelerator fabric.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops import (
    MoEConfig,
    flash_attention,
    init_moe_params,
    moe_ffn,
    ring_attention_sharded,
    top_k_gating,
    ulysses_attention,
)
from ray_tpu.parallel.pipeline import pipeline_sharded


def naive_attention(q, k, v, causal=True):
    B, S, H, hd = q.shape
    KVH = k.shape[2]
    G = H // KVH
    qg = q.reshape(B, S, KVH, G, hd)
    logits = jnp.einsum("bqkgh,btkh->bqkgt", qg, k) / np.sqrt(hd)
    if causal:
        mask = jnp.tril(jnp.ones((S, k.shape[1]), bool))
        logits = jnp.where(mask[None, :, None, None, :], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bqkgt,btkh->bqkgh", p, v)
    return out.reshape(B, S, H, hd)


@pytest.fixture(scope="module")
def qkv():
    B, S, H, KVH, hd = 2, 64, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, KVH, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, KVH, hd), jnp.float32)
    return q, k, v


class TestFlashAttention:
    def test_matches_naive_causal(self, qkv):
        q, k, v = qkv
        np.testing.assert_allclose(
            flash_attention(q, k, v, causal=True, block_q=16, block_kv=16),
            naive_attention(q, k, v, causal=True),
            atol=1e-5,
        )

    def test_matches_naive_noncausal(self, qkv):
        q, k, v = qkv
        np.testing.assert_allclose(
            flash_attention(q, k, v, causal=False, block_q=16, block_kv=16),
            naive_attention(q, k, v, causal=False),
            atol=1e-5,
        )

    def test_mha_no_gqa(self):
        B, S, H, hd = 1, 32, 2, 8
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(ks[0], (B, S, H, hd))
        k = jax.random.normal(ks[1], (B, S, H, hd))
        v = jax.random.normal(ks[2], (B, S, H, hd))
        np.testing.assert_allclose(
            flash_attention(q, k, v, block_q=8, block_kv=8),
            naive_attention(q, k, v),
            atol=1e-5,
        )


class TestRingAttention:
    @pytest.mark.parametrize("degree", [2, 4, 8])
    def test_matches_flash(self, qkv, degree):
        q, k, v = qkv
        mesh = Mesh(np.asarray(jax.devices()[:degree]), ("seq",))
        out = ring_attention_sharded(q, k, v, mesh, causal=True, block_q=16, block_kv=16)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(naive_attention(q, k, v)), atol=1e-4
        )

    def test_noncausal(self, qkv):
        q, k, v = qkv
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("seq",))
        out = ring_attention_sharded(q, k, v, mesh, causal=False, block_q=16, block_kv=16)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(naive_attention(q, k, v, causal=False)), atol=1e-4
        )

    def test_grad_flows(self, qkv):
        q, k, v = qkv
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("seq",))

        def loss(q):
            return jnp.sum(ring_attention_sharded(q, k, v, mesh, block_q=16, block_kv=16) ** 2)

        g = jax.grad(loss)(q)
        assert jnp.isfinite(g).all()
        ref = jax.grad(lambda q: jnp.sum(naive_attention(q, k, v) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref), atol=1e-3)


class TestUlysses:
    def test_matches_naive(self, qkv):
        q, k, v = qkv
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("seq",))
        fn = shard_map(
            lambda a, b, c: ulysses_attention(a, b, c, causal=True),
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
        np.testing.assert_allclose(
            np.asarray(jax.jit(fn)(q, k, v)),
            np.asarray(naive_attention(q, k, v)),
            atol=1e-4,
        )

    def test_head_divisibility_enforced(self, qkv):
        q, k, v = qkv  # KVH=2 < degree 4
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("seq",))
        fn = shard_map(
            lambda a, b, c: ulysses_attention(a, b, c),
            mesh=mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"),
            check_vma=False,
        )
        with pytest.raises(ValueError, match="n_kv_heads"):
            jax.jit(fn)(q, k, v)


class TestMoE:
    def test_gating_capacity_and_loss(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (32, 4))
        g = top_k_gating(logits, k=2, capacity_factor=1.0)
        assert g.dispatch.shape == (32, 4, 16)
        # every kept token appears exactly once per expert slot
        assert float(g.dispatch.max()) <= 1.0
        slot_usage = g.dispatch.sum(0)  # (E, C)
        assert float(slot_usage.max()) <= 1.0 + 1e-6
        assert jnp.isfinite(g.aux_loss)

    def test_dense_equivalence_k_equals_e(self):
        cfg = MoEConfig(d_model=16, d_ff=32, n_experts=2, k=2, capacity_factor=8.0)
        p = init_moe_params(jax.random.PRNGKey(2), cfg, dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
        out, _ = moe_ffn(p, x, cfg)
        xt = x.reshape(-1, 16)
        probs = jax.nn.softmax(xt @ p["router"], axis=-1)
        dense = jnp.zeros_like(xt)
        for e in range(2):
            h = jax.nn.silu(xt @ p["w_gate"][e]) * (xt @ p["w_up"][e])
            dense += probs[:, e : e + 1] * (h @ p["w_down"][e])
        np.testing.assert_allclose(
            np.asarray(out.reshape(-1, 16)), np.asarray(dense), atol=1e-4
        )

    def test_expert_parallel_sharding_compiles(self):
        """moe params sharded on `expert` axis run under jit+mesh."""
        from jax.sharding import NamedSharding

        cfg = MoEConfig(d_model=16, d_ff=32, n_experts=4, k=2)
        p = init_moe_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("expert",))
        shard = NamedSharding(mesh, P("expert"))
        p_sharded = {
            k_: (jax.device_put(v_, shard) if v_.ndim == 3 else v_)
            for k_, v_ in p.items()
        }
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
        out, aux = jax.jit(lambda pp, xx: moe_ffn(pp, xx, cfg))(p_sharded, x)
        ref, _ = moe_ffn(p, x, cfg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


class TestPipeline:
    def test_matches_sequential(self):
        P_stages = 4
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))

        def stage(params, x):
            return jnp.tanh(x @ params["w"] + params["b"])

        keys = jax.random.split(jax.random.PRNGKey(3), P_stages)
        stacked = {
            "w": jnp.stack([jax.random.normal(k_, (8, 8)) * 0.5 for k_ in keys]),
            "b": jnp.zeros((P_stages, 8)),
        }
        batch = jax.random.normal(jax.random.PRNGKey(4), (16, 8))
        out = jax.jit(pipeline_sharded(stage, stacked, mesh, microbatch_size=4))(batch)
        ref = batch
        for i in range(P_stages):
            ref = stage({"w": stacked["w"][i], "b": stacked["b"][i]}, ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_single_microbatch(self):
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("pipe",))

        def stage(params, x):
            return x + params["c"]

        stacked = {"c": jnp.asarray([1.0, 10.0])}
        batch = jnp.zeros((4, 3))
        out = jax.jit(
            pipeline_sharded(
                lambda p, x: stage(p, x), stacked, mesh, microbatch_size=4
            )
        )(batch)
        np.testing.assert_allclose(np.asarray(out), np.full((4, 3), 11.0))
